"""Eval flight recorder: end-to-end per-eval span tracing.

The batch pipeline's aggregate telemetry (`batch_worker.*` summaries,
`replay.*` counters) says *that* a stage is slow, never *which eval*
paid for it.  This module records one bounded trace per evaluation —
spans (named, timed intervals) and events (zero-duration marks) —
across every thread the eval's lifecycle touches: broker dequeue,
batch-worker gulp/simulate/assemble/launch/fetch, speculative replay
on the pool, the commit wave's ordering wait and conflict verdicts,
plan verification/apply, and the store's commit index.

Design constraints (always-on tracing must be free enough to forget):

* **O(1) per span.**  A span append is a list append under a per-trace
  lock; no allocation beyond the span record itself.
* **Bounded retention.**  One process-wide ring of `TRACE_RING` traces
  (active and completed alike — a trace that outlives the ring under
  load is dropped, never grown), `MAX_SPANS` spans per trace
  (overflow counts into `dropped`).
* **Monotonic timestamps.**  `time.monotonic()` everywhere; one
  wall-clock anchor per trace for display.
* **Opt-out, not opt-in.**  `NOMAD_TPU_TRACE=0` turns every call into
  a no-op (`Tracer.set_enabled` flips it at runtime for benches).

The tracer is a process-wide singleton (`TRACE`), like the logging
module: the broker, store and plan applier have no server reference,
and eval ids are globally unique, so per-server registries would only
add plumbing.  Cross-thread attribution is by eval id — every call
site knows which eval it is working for — with per-(trace, thread)
open-span stacks providing parent/child nesting.

Span names used in instrumented modules must be declared in
``SPAN_NAMES`` below; ``tools/check_stage_accounting.py`` lints
``batch_worker.py`` and ``plan_apply.py`` against this registry so a
renamed stage can't silently orphan its dashboard queries.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# retained traces (completed or in flight); at ~30 spans x ~150 bytes
# per trace this bounds the recorder near 5 MB
TRACE_RING = 1024
# spans per trace before overflow counting kicks in
MAX_SPANS = 256

# the documented span/event name registry.  Every `.span/.add_span/
# .event` literal in batch_worker.py and plan_apply.py must appear
# here (tools/check_stage_accounting.py); names from other modules are
# registered too so the registry is the one place to look up a trace.
SPAN_NAMES = frozenset(
    {
        # broker lifecycle
        "broker.dequeue",
        # batch pipeline stages (per-eval attribution of the
        # batch_worker.timings stages; chunk-wide spans carry a
        # `members` attr so aggregate sums match the stage timings)
        "batch_worker.gulp",
        # continuous micro-batching: `admit` spans an admission round's
        # gate+simulate+assemble work on every admitted eval (with a
        # `members` attr like the other chunk-wide stages);
        # `admit_deferred` marks an eval that arrived mid-chain but
        # failed an admission gate and was parked for the next gulp
        "batch_worker.admit",
        "batch_worker.admit_deferred",
        "batch_worker.simulate",
        "batch_worker.assemble",
        "batch_worker.launch",
        "batch_worker.fetch",
        # sharded (NOMAD_TPU_MESH) chunk dispatch/realize: the same
        # pipeline positions as launch/fetch, under their own names so
        # mesh time is separable on every trace-keyed dashboard (and
        # budgeted separately by the supervisor's stage watchdogs)
        "batch_worker.mesh_launch",
        "batch_worker.mesh_fetch",
        # global storm solver (NOMAD_TPU_STORM=1): `storm_gulp` marks
        # a family backlog drained for one coalesced solve (with the
        # member's FIFO position), `storm_solve` spans the single
        # device-side assignment solve on every member (members attr
        # like the other chunk-wide stages), `storm_decompose` the
        # per-eval plan decomposition, and `storm_fallback` marks a
        # member handed back to the serial chain (gate reason /
        # unsolved row / commit rescore) — never a dropped eval
        "batch_worker.storm_gulp",
        # policy-weighted scoring (sched/policy.py): spans one storm
        # member's weight-tensor assembly — cached-throughput lookup
        # plus the live-alloc stickiness scan — inside staging
        "batch_worker.policy_assemble",
        "batch_worker.storm_solve",
        "batch_worker.storm_decompose",
        "batch_worker.storm_fallback",
        "batch_worker.replay",
        "batch_worker.sequential",
        "batch_worker.fallback",
        # optimistic parallel replay
        "replay.speculate",
        "replay.serial_required",
        "replay.commit_wait",
        "replay.commit",
        "replay.conflict",
        "replay.serial_fallback",
        # sequential worker
        "worker.invoke_scheduler",
        # accelerator supervisor (nomad_tpu/device): failover
        # incidents get their own trace (``device:failover:<n>``,
        # rooted at device.incident); device.watchdog_trip also lands
        # on the eval whose guarded stage tripped
        "device.incident",
        "device.failover",
        "device.watchdog_trip",
        "device.state_change",
        "device.flush",
        "device.probe",
        "device.rewarm",
        "device.recover",
        # overload control plane: `ingress.shed` roots one incident
        # trace (``overload:<n>``) per excursion from NORMAL — its
        # annotations carry the trigger signals and final shed counts;
        # `server.node_down_wave` roots one trace per batched mass
        # node-death transition (``node_down_wave:<n>``) naming the
        # wave's node count, replan evals and storm family
        "ingress.shed",
        "server.node_down_wave",
        # follower scheduling fan-out (NOMAD_TPU_FANOUT=1):
        # `fanout.remote_dequeue` spans the lease RPC on every eval a
        # follower dequeued from the leader's broker (members = lease
        # batch size), `fanout.plan_submit` spans the remote
        # serialized-commit round trip into the leader's plan queue
        "fanout.remote_dequeue",
        "fanout.plan_submit",
        # plan pipeline + state commit
        "plan.evaluate",
        "plan.apply",
        # leadership failover: the applier rejected an in-flight plan
        # because leadership was revoked (the submitting worker nacks
        # the eval for redelivery under the next leadership)
        "plan.not_leader",
        "store.commit",
        "fsm.apply",
    }
)


class _NullSpan:
    """Reusable no-op context manager for disabled/unknown traces."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _SpanCtx:
    __slots__ = ("_trace", "_name", "_attrs", "_sid")

    def __init__(self, trace: "Trace", name: str, attrs: dict) -> None:
        self._trace = trace
        self._name = name
        self._attrs = attrs
        self._sid = -1

    def __enter__(self):
        self._sid = self._trace.open_span(
            self._name, time.monotonic(), self._attrs
        )
        return self

    def __exit__(self, *exc):
        self._trace.close_span(self._sid, time.monotonic())
        return False


class Trace:
    """One eval's recorded lifecycle.  Span records are small lists
    ``[sid, parent, name, start, duration, thread, attrs]`` —
    ``duration`` stays None while the span is open."""

    __slots__ = (
        "eval_id",
        "trace_id",
        "t0",
        "wall0",
        "t_end",
        "spans",
        "attrs",
        "outcome",
        "finished",
        "dropped",
        "orphans",
        "_open",
        "_seq",
        "_lock",
    )

    def __init__(self, eval_id: str, gen: int, attrs: dict) -> None:
        self.eval_id = eval_id
        self.trace_id = f"{eval_id}#{gen}"
        self.t0 = time.monotonic()
        self.wall0 = time.time()
        self.t_end: Optional[float] = None
        self.spans: List[list] = []
        self.attrs = dict(attrs)
        self.outcome: Optional[str] = None
        self.finished = False
        self.dropped = 0
        self.orphans = 0
        # thread id -> stack of open span ids (nesting is per thread;
        # cross-thread spans attach at that thread's current depth)
        self._open: Dict[int, List[int]] = {}
        self._seq = 0
        self._lock = threading.Lock()

    # -- recording -----------------------------------------------------

    def _parent_locked(self, tid: int) -> Optional[int]:
        stack = self._open.get(tid)
        return stack[-1] if stack else None

    def open_span(self, name: str, start: float, attrs: dict) -> int:
        tid = threading.get_ident()
        with self._lock:
            if len(self.spans) >= MAX_SPANS or start < self.t0:
                # over the cap, or a write from a SUPERSEDED attempt:
                # after a redelivery the old attempt may still be
                # running, and its by-eval-id writes resolve to this
                # (newer) trace — an interval that began before this
                # trace did belongs to the old generation, not here
                self.dropped += 1
                return -1
            sid = self._seq
            self._seq += 1
            self.spans.append(
                [
                    sid,
                    self._parent_locked(tid),
                    name,
                    start,
                    None,
                    threading.current_thread().name,
                    attrs,
                ]
            )
            self._open.setdefault(tid, []).append(sid)
            return sid

    def close_span(self, sid: int, end: float) -> None:
        if sid < 0:
            return
        tid = threading.get_ident()
        with self._lock:
            stack = self._open.get(tid)
            if stack and sid in stack:
                # pop through sid: a crash that skipped inner exits
                # must not leave phantom parents for later spans
                while stack and stack.pop() != sid:
                    pass
                if not stack:
                    self._open.pop(tid, None)
            for span in self.spans:
                if span[0] == sid:
                    span[4] = end - span[3]
                    return

    def add_span(
        self, name: str, start: float, duration: float, attrs: dict
    ) -> None:
        """Record an already-timed interval (stage times measured once
        per chunk/run and attributed to each member eval)."""
        tid = threading.get_ident()
        with self._lock:
            if len(self.spans) >= MAX_SPANS or start < self.t0:
                # see open_span: pre-t0 starts are a superseded
                # attempt's writes (best-effort — a stale write whose
                # clock reads after this trace began is
                # indistinguishable and slips through)
                self.dropped += 1
                return
            sid = self._seq
            self._seq += 1
            self.spans.append(
                [
                    sid,
                    self._parent_locked(tid),
                    name,
                    start,
                    duration,
                    threading.current_thread().name,
                    attrs,
                ]
            )

    def annotate(self, attrs: dict) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def finish(self, outcome: str) -> None:
        with self._lock:
            if self.finished:
                return
            self.finished = True
            self.t_end = time.monotonic()
            # a batch-worker path may have annotated a richer outcome
            # ("speculative", "prescored", "sequential") — but only a
            # successful ack consumes it: a nack or a redelivery
            # supersede describes an attempt that did NOT stick, and
            # must not masquerade as the annotated success
            annotated = self.attrs.pop("outcome", None)
            self.outcome = (
                annotated if annotated and outcome == "ack" else outcome
            )
            self.orphans = sum(
                1 for s in self.spans if s[4] is None
            )

    # -- serialization -------------------------------------------------

    def duration_ms(self) -> Optional[float]:
        if self.t_end is None:
            return None
        end = self.t_end
        with self._lock:
            for s in self.spans:
                if s[4] is not None:
                    end = max(end, s[3] + s[4])
        return (end - self.t0) * 1000.0

    def summary(self) -> Dict:
        return {
            "eval_id": self.eval_id,
            "trace_id": self.trace_id,
            "start": self.wall0,
            "outcome": self.outcome,
            "complete": self.finished,
            "duration_ms": self.duration_ms(),
            "spans": len(self.spans),
            "dropped": self.dropped,
            "orphans": self.orphans,
            "attrs": dict(self.attrs),
        }

    def to_dict(self) -> Dict:
        out = self.summary()
        with self._lock:
            out["spans"] = [
                {
                    "id": sid,
                    "parent": parent,
                    "name": name,
                    "off_ms": (start - self.t0) * 1000.0,
                    "dur_ms": (
                        duration * 1000.0
                        if duration is not None
                        else None
                    ),
                    "thread": thread,
                    "attrs": dict(attrs),
                }
                for sid, parent, name, start, duration, thread, attrs
                in self.spans
            ]
        return out


class Tracer:
    def __init__(self, ring: int = TRACE_RING) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._ring_cap = ring
        # newest trace per eval id (ring members only) — the append
        # surface every instrumented call site goes through
        self._by_id: Dict[str, Trace] = {}
        self._gen = itertools.count()
        self.enabled = os.environ.get("NOMAD_TPU_TRACE", "1") != "0"
        # happens-before sanitizer (NOMAD_TPU_TSAN=1)
        from .tsan import maybe_instrument

        maybe_instrument(self, "Tracer")

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    # -- lifecycle -----------------------------------------------------

    def begin(
        self, eval_id: str, root_span: str = "broker.dequeue", **attrs
    ) -> None:
        """Start (or restart, on redelivery) an eval's trace; records
        ``root_span`` (default `broker.dequeue`) as the root event —
        non-eval traces (the device supervisor's failover incidents)
        pass their own root name."""
        if not self.enabled or not eval_id:
            return
        trace = Trace(eval_id, next(self._gen), attrs)
        with self._lock:
            prior = self._by_id.get(eval_id)
            if prior is not None and not prior.finished:
                prior.finish("superseded")
            self._by_id[eval_id] = trace
            self._ring.append(trace)
            while len(self._ring) > self._ring_cap:
                evicted = self._ring.popleft()
                if self._by_id.get(evicted.eval_id) is evicted:
                    del self._by_id[evicted.eval_id]
        trace.add_span(root_span, trace.t0, 0.0, attrs)

    def finish(self, eval_id: str, outcome: str) -> None:
        if not self.enabled:
            return
        trace = self._by_id.get(eval_id)
        if trace is not None:
            trace.finish(outcome)

    # -- recording -----------------------------------------------------

    def span(self, eval_id: str, name: str, **attrs):
        """Context manager timing a span on the eval's trace; no-op
        when tracing is off or the eval has no trace."""
        if not self.enabled:
            return _NULL
        trace = self._by_id.get(eval_id)
        if trace is None:
            return _NULL
        return _SpanCtx(trace, name, attrs)

    def add_span(
        self, eval_id: str, name: str, start: float,
        duration: float, **attrs,
    ) -> None:
        if not self.enabled:
            return
        trace = self._by_id.get(eval_id)
        if trace is not None:
            trace.add_span(name, start, duration, attrs)

    def event(self, eval_id: str, name: str, **attrs) -> None:
        if not self.enabled:
            return
        trace = self._by_id.get(eval_id)
        if trace is not None:
            trace.add_span(name, time.monotonic(), 0.0, attrs)

    def annotate(self, eval_id: str, **attrs) -> None:
        if not self.enabled:
            return
        trace = self._by_id.get(eval_id)
        if trace is not None:
            trace.annotate(attrs)

    # -- reads ---------------------------------------------------------

    def trace_id_of(self, eval_id: str) -> str:
        """Current trace id for an eval (newest generation), "" when
        untracked — the placement-explanation cross-link."""
        trace = self._by_id.get(eval_id)
        return trace.trace_id if trace is not None else ""

    def get(self, ref: str) -> Optional[Dict]:
        """Resolve a bare eval id (newest generation) OR a full
        trace id (``<eval_id>#<gen>``, as listed by /v1/traces) —
        an id copied from the listing must dereference even after a
        redelivery superseded that generation."""
        trace = self._by_id.get(ref)
        if trace is not None:
            return trace.to_dict()
        if "#" in ref:
            with self._lock:
                candidates = list(self._ring)
            for trace in reversed(candidates):
                if trace.trace_id == ref:
                    return trace.to_dict()
        return None

    def recent(
        self,
        slow_ms: Optional[float] = None,
        outcome: Optional[str] = None,
        limit: int = 64,
        full: bool = False,
    ) -> List[Dict]:
        """Completed traces, newest first, optionally filtered to
        slow (>= slow_ms total) or outcome-matching ones."""
        with self._lock:
            candidates = list(self._ring)
        out: List[Dict] = []
        for trace in reversed(candidates):
            if not trace.finished:
                continue
            if outcome is not None and trace.outcome != outcome:
                continue
            if slow_ms is not None:
                dur = trace.duration_ms()
                if dur is None or dur < slow_ms:
                    continue
            out.append(trace.to_dict() if full else trace.summary())
            if len(out) >= limit:
                break
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_id.clear()


TRACE = Tracer()

__all__ = [
    "MAX_SPANS",
    "SPAN_NAMES",
    "TRACE",
    "TRACE_RING",
    "Trace",
    "Tracer",
]
