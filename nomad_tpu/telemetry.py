"""Telemetry: in-memory metrics with counters, gauges and timing samples
(reference go-metrics usage; sinks like statsd/prometheus are
export-format adapters over this store — `dump()` is the /v1/metrics
payload, `prometheus_text()` the scrape format).
"""
from __future__ import annotations

import os
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

# cluster-scope observability telemetry, zero-registered at Server
# construction (the `cluster-obs-metrics` nomadlint rule enforces
# registry membership for every obs.* / cluster.* emission)
CLUSTER_OBS_COUNTERS = (
    # leader side of cross-server trace stitching (cluster.py)
    "cluster.segments_absorbed",  # follower segments stitched in
    "cluster.segment_spans",  # spans absorbed from segments
    # leader fan-in queries (/v1/cluster/*)
    "cluster.fanin_queries",
    "cluster.fanin_unreachable",  # per-peer timeouts/failures
    # metric time-series history (MetricsHistory below)
    "obs.history_snapshots",
)
CLUSTER_OBS_GAUGES = (
    "obs.history_windows",  # windows currently retained in the ring
)


def obs_history_enabled() -> bool:
    return os.environ.get("NOMAD_TPU_OBS_HISTORY", "1") != "0"


def obs_history_windows() -> int:
    try:
        return max(
            2, int(os.environ.get("NOMAD_TPU_OBS_HISTORY_N", "60"))
        )
    except ValueError:
        return 60


def obs_history_interval_s() -> float:
    try:
        return max(
            0.05,
            float(os.environ.get("NOMAD_TPU_OBS_HISTORY_S", "10")),
        )
    except ValueError:
        return 10.0


def percentile(ordered: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list — the single
    shared implementation (summary snapshots here, the device
    supervisor's probe-latency status) so /v1/metrics and /v1/device
    can never report different p99s for the same ring."""
    if not ordered:
        return 0.0
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


class _Summary:
    __slots__ = (
        "count", "total", "min", "max", "_ring", "_ring_ex",
        "_ring_pos",
    )

    # sliding window for percentile estimates: large enough for a
    # stable p99 over recent traffic, small enough to stay O(1) memory
    RING = 2048
    # exemplar trace ids reported per snapshot (the p99 ring entries)
    EXEMPLARS = 4

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        # -inf, not 0.0: an all-negative sample stream must report its
        # true (negative) max, mirroring min's +inf idiom
        self.max = float("-inf")
        self._ring: List[float] = []
        # exemplar per ring slot: the trace (eval) id that produced
        # the sample, or None — links a slow percentile to the eval
        # that caused it (/v1/traces/<id>)
        self._ring_ex: List[Optional[str]] = []
        self._ring_pos = 0

    def add(self, value: float, exemplar: Optional[str] = None) -> None:
        self.count += 1
        self.total += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if len(self._ring) < self.RING:
            self._ring.append(value)
            self._ring_ex.append(exemplar)
        else:
            self._ring[self._ring_pos] = value
            self._ring_ex[self._ring_pos] = exemplar
            self._ring_pos = (self._ring_pos + 1) % self.RING

    def _percentile(self, ordered: List[float], q: float) -> float:
        return percentile(ordered, q)

    def _exemplars(self, p99: float) -> List[Dict]:
        """Trace refs of the ring entries at or above p99, slowest
        first — the samples an operator will want to explain.  A ref
        is whatever the caller passed (callers pass eval ids), and
        /v1/traces/<ref> resolves it — to the newest generation when
        the eval was redelivered."""
        tagged = sorted(
            (
                (v, ex)
                for v, ex in zip(self._ring, self._ring_ex)
                if ex is not None and v >= p99
            ),
            reverse=True,
        )
        return [
            {"value": v, "trace_id": ex}
            for v, ex in tagged[: self.EXEMPLARS]
        ]

    def snapshot(self) -> Dict:
        ordered = sorted(self._ring)
        p99 = self._percentile(ordered, 0.99)
        return {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count if self.count else 0.0,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
            # percentiles over the sliding window (last RING samples)
            "p50": self._percentile(ordered, 0.50),
            "p90": self._percentile(ordered, 0.90),
            "p99": p99,
            # trace exemplars for the slow tail (eval flight recorder)
            "exemplars": self._exemplars(p99),
        }


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._samples: Dict[str, _Summary] = defaultdict(_Summary)
        # happens-before sanitizer (NOMAD_TPU_TSAN=1)
        from .tsan import maybe_instrument

        maybe_instrument(self, "Metrics")

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def add_sample(
        self, name: str, value: float,
        exemplar: Optional[str] = None,
    ) -> None:
        with self._lock:
            self._samples[name].add(value, exemplar)

    def get_counter(self, name: str) -> float:
        """O(1) single-counter read (tests/operators polling one hot
        counter — e.g. the optimistic-replay `replay.*` family —
        shouldn't pay for a full dump() copy)."""
        with self._lock:
            return self._counters.get(name, 0.0)

    def get_gauge(self, name: str) -> Optional[float]:
        """O(1) single-gauge read; None when the gauge was never set."""
        with self._lock:
            return self._gauges.get(name)

    def get_sample(self, name: str) -> Optional[Dict]:
        """Snapshot of ONE summary (None when never sampled) without
        paying for a full dump() copy — the overload controller polls
        the flight-recorder latency p99 at mode-evaluation cadence."""
        with self._lock:
            summary = self._samples.get(name)
            return summary.snapshot() if summary is not None else None

    def preregister(
        self,
        counters=(),
        gauges=(),
        samples=(),
    ) -> None:
        """Zero-register metric names so they appear on /v1/metrics and
        prometheus scrapes from process start (a `device.failover`
        counter that only materializes DURING an incident would make
        absence-of-series indistinguishable from absence-of-failures
        on every dashboard)."""
        with self._lock:
            for name in counters:
                self._counters[name] += 0.0
            for name in gauges:
                self._gauges.setdefault(name, 0.0)
            for name in samples:
                self._samples[name]  # defaultdict materializes it

    @contextmanager
    def measure(self, name: str):
        """(reference go-metrics MeasureSince)"""
        start = time.monotonic()
        try:
            yield
        finally:
            self.add_sample(name, (time.monotonic() - start) * 1000.0)

    def dump(self) -> Dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "samples": {
                    k: s.snapshot() for k, s in self._samples.items()
                },
            }

    def dump_lean(self) -> Dict:
        """dump() without the per-summary exemplar scan — the history
        snapshotter's cadence payload (exemplar trace refs are a
        point-in-time debugging surface, not a time series)."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "samples": {
                    k: {
                        "count": s.count,
                        "p50": percentile(sorted(s._ring), 0.50),
                        "p99": percentile(sorted(s._ring), 0.99),
                    }
                    for k, s in self._samples.items()
                },
            }

    def prometheus_text(self) -> str:
        lines: List[str] = []
        # esc() is lossy (both "." and "-" map to "_"), so two
        # distinct store names can collide into one scrape name —
        # which Prometheus rejects as a duplicate series.  First
        # occurrence (sorted order, counters < gauges < summaries)
        # wins; later collisions are skipped with a comment so the
        # scrape stays valid and the loss is visible.
        emitted: set = set()

        def esc(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        def claim(name: str) -> Optional[str]:
            base = esc(name)
            if base in emitted:
                lines.append(
                    f"# collision: {name} already emitted as {base}"
                )
                return None
            emitted.add(base)
            return base

        with self._lock:
            for name, value in sorted(self._counters.items()):
                base = claim(name)
                if base is None:
                    continue
                lines.append(f"# TYPE {base} counter")
                lines.append(f"{base} {value}")
            for name, value in sorted(self._gauges.items()):
                base = claim(name)
                if base is None:
                    continue
                lines.append(f"# TYPE {base} gauge")
                lines.append(f"{base} {value}")
            for name, summary in sorted(self._samples.items()):
                base = claim(name)
                if base is None:
                    continue
                snap = summary.snapshot()
                lines.append(f"# TYPE {base} summary")
                lines.append(f"{base}_count {snap['count']}")
                lines.append(f"{base}_sum {snap['sum']}")
                for q, key in (
                    ("0.5", "p50"),
                    ("0.9", "p90"),
                    ("0.99", "p99"),
                ):
                    lines.append(
                        f'{base}{{quantile="{q}"}} {snap[key]}'
                    )
        return "\n".join(lines) + "\n"


class MetricsHistory:
    """Fixed-size ring of periodic metric snapshots — the first way to
    see "p99 over the last N minutes" without an external scraper, and
    the training-data surface the future self-tuning controller reads.

    Every ``NOMAD_TPU_OBS_HISTORY_S`` seconds a snapshot thread
    (`obs-history`) captures all registered counters (cumulative),
    gauges (point-in-time) and sample summaries (count + p50/p99 over
    the summary's sliding window, read at the window boundary) into a
    ``NOMAD_TPU_OBS_HISTORY_N``-deep ring.  Memory is bounded at
    windows x registered-metric-count small floats — sizing math in
    docs/ARCHITECTURE.md "Cluster observability".

    Served as /v1/metrics/history, captured in the operator debug
    bundle, and fanned in cluster-wide via /v1/cluster/* queries.
    """

    def __init__(
        self,
        metrics: Metrics,
        windows: Optional[int] = None,
        interval_s: Optional[float] = None,
    ) -> None:
        self.metrics = metrics
        self.enabled = obs_history_enabled()
        self.windows = (
            windows if windows is not None else obs_history_windows()
        )
        self.interval_s = (
            interval_s
            if interval_s is not None
            else obs_history_interval_s()
        )
        self._ring: deque = deque(maxlen=self.windows)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="obs-history", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.snapshot_once()

    # -- capture -------------------------------------------------------

    def snapshot_once(self) -> Dict:
        """Capture one window (also the debug-bundle/test entry point,
        so a capture never has to wait out the interval)."""
        dump = self.metrics.dump_lean()
        window = {
            "t": time.time(),
            "counters": dump["counters"],
            "gauges": dump["gauges"],
            "samples": dump["samples"],
        }
        with self._lock:
            self._ring.append(window)
            retained = len(self._ring)
        self.metrics.incr("obs.history_snapshots")
        self.metrics.set_gauge("obs.history_windows", float(retained))
        return window

    # -- reads ---------------------------------------------------------

    def to_dict(self) -> Dict:
        """/v1/metrics/history payload: every retained window, oldest
        first, plus the sizing that produced them."""
        with self._lock:
            windows = list(self._ring)
        return {
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "max_windows": self.windows,
            "windows": windows,
        }

    def series(self, name: str) -> List[Dict]:
        """One metric's time series across the retained windows —
        [{t, value}] for counters/gauges, [{t, count, p50, p99}] for
        samples."""
        with self._lock:
            windows = list(self._ring)
        out: List[Dict] = []
        for w in windows:
            if name in w["samples"]:
                entry = dict(w["samples"][name])
                entry["t"] = w["t"]
                out.append(entry)
            elif name in w["counters"]:
                out.append({"t": w["t"], "value": w["counters"][name]})
            elif name in w["gauges"]:
                out.append({"t": w["t"], "value": w["gauges"][name]})
        return out
