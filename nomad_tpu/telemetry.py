"""Telemetry: in-memory metrics with counters, gauges and timing samples
(reference go-metrics usage; sinks like statsd/prometheus are
export-format adapters over this store — `dump()` is the /v1/metrics
payload, `prometheus_text()` the scrape format).
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple


def percentile(ordered: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list — the single
    shared implementation (summary snapshots here, the device
    supervisor's probe-latency status) so /v1/metrics and /v1/device
    can never report different p99s for the same ring."""
    if not ordered:
        return 0.0
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


class _Summary:
    __slots__ = (
        "count", "total", "min", "max", "_ring", "_ring_ex",
        "_ring_pos",
    )

    # sliding window for percentile estimates: large enough for a
    # stable p99 over recent traffic, small enough to stay O(1) memory
    RING = 2048
    # exemplar trace ids reported per snapshot (the p99 ring entries)
    EXEMPLARS = 4

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        # -inf, not 0.0: an all-negative sample stream must report its
        # true (negative) max, mirroring min's +inf idiom
        self.max = float("-inf")
        self._ring: List[float] = []
        # exemplar per ring slot: the trace (eval) id that produced
        # the sample, or None — links a slow percentile to the eval
        # that caused it (/v1/traces/<id>)
        self._ring_ex: List[Optional[str]] = []
        self._ring_pos = 0

    def add(self, value: float, exemplar: Optional[str] = None) -> None:
        self.count += 1
        self.total += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if len(self._ring) < self.RING:
            self._ring.append(value)
            self._ring_ex.append(exemplar)
        else:
            self._ring[self._ring_pos] = value
            self._ring_ex[self._ring_pos] = exemplar
            self._ring_pos = (self._ring_pos + 1) % self.RING

    def _percentile(self, ordered: List[float], q: float) -> float:
        return percentile(ordered, q)

    def _exemplars(self, p99: float) -> List[Dict]:
        """Trace refs of the ring entries at or above p99, slowest
        first — the samples an operator will want to explain.  A ref
        is whatever the caller passed (callers pass eval ids), and
        /v1/traces/<ref> resolves it — to the newest generation when
        the eval was redelivered."""
        tagged = sorted(
            (
                (v, ex)
                for v, ex in zip(self._ring, self._ring_ex)
                if ex is not None and v >= p99
            ),
            reverse=True,
        )
        return [
            {"value": v, "trace_id": ex}
            for v, ex in tagged[: self.EXEMPLARS]
        ]

    def snapshot(self) -> Dict:
        ordered = sorted(self._ring)
        p99 = self._percentile(ordered, 0.99)
        return {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count if self.count else 0.0,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
            # percentiles over the sliding window (last RING samples)
            "p50": self._percentile(ordered, 0.50),
            "p90": self._percentile(ordered, 0.90),
            "p99": p99,
            # trace exemplars for the slow tail (eval flight recorder)
            "exemplars": self._exemplars(p99),
        }


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._samples: Dict[str, _Summary] = defaultdict(_Summary)
        # happens-before sanitizer (NOMAD_TPU_TSAN=1)
        from .tsan import maybe_instrument

        maybe_instrument(self, "Metrics")

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def add_sample(
        self, name: str, value: float,
        exemplar: Optional[str] = None,
    ) -> None:
        with self._lock:
            self._samples[name].add(value, exemplar)

    def get_counter(self, name: str) -> float:
        """O(1) single-counter read (tests/operators polling one hot
        counter — e.g. the optimistic-replay `replay.*` family —
        shouldn't pay for a full dump() copy)."""
        with self._lock:
            return self._counters.get(name, 0.0)

    def get_gauge(self, name: str) -> Optional[float]:
        """O(1) single-gauge read; None when the gauge was never set."""
        with self._lock:
            return self._gauges.get(name)

    def get_sample(self, name: str) -> Optional[Dict]:
        """Snapshot of ONE summary (None when never sampled) without
        paying for a full dump() copy — the overload controller polls
        the flight-recorder latency p99 at mode-evaluation cadence."""
        with self._lock:
            summary = self._samples.get(name)
            return summary.snapshot() if summary is not None else None

    def preregister(
        self,
        counters=(),
        gauges=(),
        samples=(),
    ) -> None:
        """Zero-register metric names so they appear on /v1/metrics and
        prometheus scrapes from process start (a `device.failover`
        counter that only materializes DURING an incident would make
        absence-of-series indistinguishable from absence-of-failures
        on every dashboard)."""
        with self._lock:
            for name in counters:
                self._counters[name] += 0.0
            for name in gauges:
                self._gauges.setdefault(name, 0.0)
            for name in samples:
                self._samples[name]  # defaultdict materializes it

    @contextmanager
    def measure(self, name: str):
        """(reference go-metrics MeasureSince)"""
        start = time.monotonic()
        try:
            yield
        finally:
            self.add_sample(name, (time.monotonic() - start) * 1000.0)

    def dump(self) -> Dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "samples": {
                    k: s.snapshot() for k, s in self._samples.items()
                },
            }

    def prometheus_text(self) -> str:
        lines: List[str] = []
        # esc() is lossy (both "." and "-" map to "_"), so two
        # distinct store names can collide into one scrape name —
        # which Prometheus rejects as a duplicate series.  First
        # occurrence (sorted order, counters < gauges < summaries)
        # wins; later collisions are skipped with a comment so the
        # scrape stays valid and the loss is visible.
        emitted: set = set()

        def esc(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        def claim(name: str) -> Optional[str]:
            base = esc(name)
            if base in emitted:
                lines.append(
                    f"# collision: {name} already emitted as {base}"
                )
                return None
            emitted.add(base)
            return base

        with self._lock:
            for name, value in sorted(self._counters.items()):
                base = claim(name)
                if base is None:
                    continue
                lines.append(f"# TYPE {base} counter")
                lines.append(f"{base} {value}")
            for name, value in sorted(self._gauges.items()):
                base = claim(name)
                if base is None:
                    continue
                lines.append(f"# TYPE {base} gauge")
                lines.append(f"{base} {value}")
            for name, summary in sorted(self._samples.items()):
                base = claim(name)
                if base is None:
                    continue
                snap = summary.snapshot()
                lines.append(f"# TYPE {base} summary")
                lines.append(f"{base}_count {snap['count']}")
                lines.append(f"{base}_sum {snap['sum']}")
                for q, key in (
                    ("0.5", "p50"),
                    ("0.9", "p90"),
                    ("0.99", "p99"),
                ):
                    lines.append(
                        f'{base}{{quantile="{q}"}} {snap[key]}'
                    )
        return "\n".join(lines) + "\n"
