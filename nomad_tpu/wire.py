"""Python side of the wire protocol (see native/wire.h).

Provides the same msgpack-compatible wide-form codec in pure Python, a
ctypes binding to the native library when built (`make -C native`), and
the framed-socket helpers both the bridge service and in-Python clients
use.  Pure-Python and native codecs are byte-identical (tested), so
either side of a connection may use either implementation.
"""
from __future__ import annotations

import ctypes
import json
import os
import socket
import struct
from typing import Any, Optional, Tuple

MAX_FRAME = 64 << 20

# ---------------------------------------------------------------------------
# pure-Python codec
# ---------------------------------------------------------------------------


def encode(value: Any) -> bytes:
    out = bytearray()
    _encode(value, out)
    return bytes(out)


def _encode(value: Any, out: bytearray) -> None:
    if value is None:
        out.append(0xC0)
    elif value is True:
        out.append(0xC3)
    elif value is False:
        out.append(0xC2)
    elif isinstance(value, int):
        out.append(0xD3)
        out += struct.pack(">q", value)
    elif isinstance(value, float):
        out.append(0xCB)
        out += struct.pack(">d", value)
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out.append(0xDB)
        out += struct.pack(">I", len(raw))
        out += raw
    elif isinstance(value, (bytes, bytearray)):
        out.append(0xC6)
        out += struct.pack(">I", len(value))
        out += bytes(value)
    elif isinstance(value, (list, tuple)):
        out.append(0xDD)
        out += struct.pack(">I", len(value))
        for item in value:
            _encode(item, out)
    elif isinstance(value, dict):
        out.append(0xDF)
        out += struct.pack(">I", len(value))
        for k, v in value.items():
            _encode(str(k), out)
            _encode(v, out)
    else:
        raise TypeError(f"cannot encode {type(value).__name__}")


def decode(data: bytes) -> Any:
    value, offset = _decode(data, 0)
    if offset != len(data):
        raise ValueError("trailing bytes after wire value")
    return value


def _decode(data: bytes, offset: int) -> Tuple[Any, int]:
    tag = data[offset]
    offset += 1
    if tag == 0xC0:
        return None, offset
    if tag == 0xC2:
        return False, offset
    if tag == 0xC3:
        return True, offset
    if tag == 0xD3:
        return struct.unpack_from(">q", data, offset)[0], offset + 8
    if tag == 0xCB:
        return struct.unpack_from(">d", data, offset)[0], offset + 8
    if tag == 0xDB:
        (n,) = struct.unpack_from(">I", data, offset)
        offset += 4
        return data[offset : offset + n].decode("utf-8"), offset + n
    if tag == 0xC6:
        (n,) = struct.unpack_from(">I", data, offset)
        offset += 4
        return bytes(data[offset : offset + n]), offset + n
    if tag == 0xDD:
        (n,) = struct.unpack_from(">I", data, offset)
        offset += 4
        items = []
        for _ in range(n):
            item, offset = _decode(data, offset)
            items.append(item)
        return items, offset
    if tag == 0xDF:
        (n,) = struct.unpack_from(">I", data, offset)
        offset += 4
        obj = {}
        for _ in range(n):
            key, offset = _decode(data, offset)
            value, offset = _decode(data, offset)
            obj[key] = value
        return obj, offset
    raise ValueError(f"unknown wire tag 0x{tag:02x}")


# ---------------------------------------------------------------------------
# framed sockets
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise ValueError("frame exceeds sanity cap")
    return _recv_exact(sock, length)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def call(sock: socket.socket, method: str, body: Any) -> Any:
    """One RPC round trip from Python (mirrors nw_call_json)."""
    send_frame(sock, encode([method, body]))
    resp = recv_frame(sock)
    if resp is None:
        raise ConnectionError("connection closed mid-call")
    return decode(resp)


# ---------------------------------------------------------------------------
# native library binding
# ---------------------------------------------------------------------------

_NATIVE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "libnomadwire.so",
)


class NativeWire:
    """ctypes binding over native/libnomadwire.so."""

    def __init__(self, path: str = _NATIVE_PATH) -> None:
        self.lib = ctypes.CDLL(path)
        self.lib.nw_encode_json.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        self.lib.nw_decode_to_json.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        self.lib.nw_call_json.argtypes = [
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        self.lib.nw_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        self.lib.nw_free.argtypes = [ctypes.c_void_p]
        self.lib.nw_version.restype = ctypes.c_char_p

    @staticmethod
    def available(path: str = _NATIVE_PATH) -> bool:
        return os.path.exists(path)

    def version(self) -> str:
        return self.lib.nw_version().decode()

    def encode_json(self, document: Any) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = self.lib.nw_encode_json(
            json.dumps(document).encode(), ctypes.byref(out),
            ctypes.byref(out_len),
        )
        if rc != 0:
            raise ValueError(f"nw_encode_json failed: {rc}")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self.lib.nw_free(out)

    def decode_json(self, data: bytes) -> Any:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        out = ctypes.c_char_p()
        rc = self.lib.nw_decode_to_json(
            buf, len(data), ctypes.byref(out)
        )
        if rc != 0:
            raise ValueError(f"nw_decode_to_json failed: {rc}")
        try:
            return json.loads(out.value.decode())
        finally:
            self.lib.nw_free(out)

    def connect(self, host: str, port: int) -> int:
        fd = self.lib.nw_connect(host.encode(), port)
        if fd < 0:
            raise ConnectionError(f"nw_connect failed: {fd}")
        return fd

    def close(self, fd: int) -> None:
        self.lib.nw_close(fd)

    def call_json(self, fd: int, method: str, body: Any) -> Any:
        out = ctypes.c_char_p()
        rc = self.lib.nw_call_json(
            fd, method.encode(), json.dumps(body).encode(),
            ctypes.byref(out),
        )
        if rc != 0:
            raise ConnectionError(f"nw_call_json failed: {rc}")
        try:
            return json.loads(out.value.decode())
        finally:
            self.lib.nw_free(out)
