"""Allocation runner: one per allocation, owns the task runners and the
client-status fan-in (reference client/allocrunner/alloc_runner.go:35,
task-state fan-in :443 handleTaskStateUpdates).
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional

from ..structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_CLIENT_STATUS_RUNNING,
    Allocation,
    TaskState,
)
from .task_runner import TASK_STATE_DEAD, TASK_STATE_RUNNING, TaskRunner


class AllocRunner:
    def __init__(
        self,
        alloc: Allocation,
        data_dir: str = "",
        on_update: Optional[Callable[[Allocation], None]] = None,
        drivers: Optional[Dict[str, object]] = None,
        secrets=None,
        catalog=None,
        csi_manager=None,
        csi_resolver=None,
        node=None,
        region: str = "global",
        prev_watcher=None,
        device_manager=None,
    ) -> None:
        self.secrets = secrets
        self.catalog = catalog
        self.csi_manager = csi_manager
        self.csi_resolver = csi_resolver
        self.alloc = alloc
        self.on_update = on_update
        self.prev_watcher = prev_watcher
        self.device_manager = device_manager
        self._setup_error: str = ""
        self._lock = threading.Lock()
        self.task_runners: Dict[str, TaskRunner] = {}
        self._destroyed = False

        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if tg is None:
            raise ValueError(
                f"alloc {alloc.id} references unknown task group "
                f"{alloc.task_group!r}"
            )
        self.tg = tg
        alloc_dir = (
            os.path.join(data_dir, "allocs", alloc.id) if data_dir else ""
        )
        # allocdir layout (client/allocdir): shared alloc/ + per-task
        # local/secrets/tmp, built lazily in run()
        self.alloc_dir_obj = None
        if data_dir:
            from .allocdir import AllocDir

            self.alloc_dir_obj = AllocDir(
                os.path.join(data_dir, "allocs"), alloc.id
            )
        env = {
            "NOMAD_ALLOC_ID": alloc.id,
            "NOMAD_ALLOC_NAME": alloc.name,
            "NOMAD_ALLOC_INDEX": str(alloc.index()),
            "NOMAD_JOB_NAME": job.name if job else "",
            "NOMAD_JOB_ID": job.id if job else "",
            "NOMAD_GROUP_NAME": tg.name,
            "NOMAD_NAMESPACE": alloc.namespace,
            "NOMAD_DC": "",
            "NOMAD_ALLOC_DIR": alloc_dir,
        }
        is_batch = job is not None and job.type == "batch"
        for task in tg.tasks:
            driver = None
            if drivers is not None:
                driver = drivers.get(task.driver)
            task_dir = None
            task_env = None
            if self.alloc_dir_obj is not None:
                from .taskenv import Builder

                task_dir = self.alloc_dir_obj.new_task_dir(task.name)
                b = Builder().set_alloc(alloc, job, tg)
                if node is not None:
                    b.set_node(node, region)
                b.set_task(task, task_dir)
                # group-level port offers (AllocatedSharedResources)
                if alloc.allocated_resources is not None:
                    for p in alloc.allocated_resources.shared.ports:
                        b.set_ports(
                            {p.label: p.value},
                            ip=p.host_ip or "127.0.0.1",
                        )
                task_env = b.build()
            # device reservations -> env pinning (devices.py; reference
            # taskrunner/device_hook.go).  A reservation that cannot be
            # honored fails the alloc in run(); starting unpinned would
            # let the task grab devices reserved by its neighbors.
            extra_env = {}
            if (
                self.device_manager is not None
                and alloc.allocated_resources is not None
            ):
                tr_res = alloc.allocated_resources.tasks.get(task.name)
                for dev in tr_res.devices if tr_res else ():
                    try:
                        spec = self.device_manager.reserve(
                            alloc.id, dev.vendor, dev.type, dev.name,
                            dev.device_ids,
                        )
                        extra_env.update(spec.envs)
                    except KeyError as exc:
                        self._setup_error = (
                            f"device reservation failed: {exc}"
                        )
            self.task_runners[task.name] = TaskRunner(
                alloc_id=alloc.id,
                task=task,
                restart_policy=tg.restart_policy,
                batch=is_batch,
                alloc_dir=alloc_dir,
                env={**env, "NOMAD_TASK_NAME": task.name},
                on_state_change=self._on_task_state,
                driver=driver,
                secrets=secrets,
                catalog=catalog,
                task_dir=task_dir,
                task_env=task_env,
                payload=(job.payload if job is not None else b""),
                extra_env=extra_env,
            )

    # ------------------------------------------------------------------

    def run(self) -> None:
        self.alloc.client_status = ALLOC_CLIENT_STATUS_PENDING
        if self.alloc_dir_obj is not None:
            self.alloc_dir_obj.build()
        # wait for + migrate from the previous allocation (reference
        # allocrunner migrate hook via client/allocwatcher); the wait
        # happens off-thread so the client's watch loop never blocks
        if self.prev_watcher is not None:
            threading.Thread(
                target=self._wait_prev_then_start,
                name=f"allocwatch-{self.alloc.id[:8]}",
                daemon=True,
            ).start()
            return
        self._start_tasks()

    def _wait_prev_then_start(self) -> None:
        try:
            while not self.prev_watcher.wait(timeout=0.25):
                with self._lock:
                    if self._destroyed:
                        return
            with self._lock:
                if self._destroyed:
                    return
            if self.alloc_dir_obj is not None:
                self.prev_watcher.migrate(self.alloc_dir_obj)
        finally:
            # releases the predecessor's GC pin whether or not the
            # migration ran (client.py sets on_done)
            on_done = getattr(self.prev_watcher, "on_done", None)
            if on_done is not None:
                on_done()
        self._start_tasks()

    def _start_tasks(self) -> None:
        if self._setup_error:
            with self._lock:
                self.alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
            if self.device_manager is not None:
                self.device_manager.free(self.alloc.id)
            if self.on_update:
                self.on_update(self.alloc)
            return
        if not self._csi_mount():
            return
        for tr in self.task_runners.values():
            tr.start()

    def _csi_mount(self) -> bool:
        """Stage+publish requested CSI volumes before any task starts
        (reference client/allocrunner/csi_hook.go).  A mount failure
        fails the whole alloc, which triggers rescheduling."""
        if self.csi_manager is None:
            return True
        from .csi import CSIPluginError

        for req in self.tg.volumes.values():
            if req.type != "csi":
                continue
            vol = None
            if self.csi_resolver is not None:
                vol = self.csi_resolver(self.alloc.namespace, req.source)
            try:
                if vol is None:
                    raise CSIPluginError(
                        f"unknown CSI volume {req.source!r}"
                    )
                self.csi_manager.mount_volume(
                    vol.plugin_id,
                    vol.id,
                    self.alloc.id,
                    req.read_only,
                    access_mode=vol.access_mode,
                    attachment_mode=vol.attachment_mode,
                )
            except CSIPluginError:
                self.csi_manager.unmount_all(self.alloc.id)
                with self._lock:
                    self.alloc.client_status = (
                        ALLOC_CLIENT_STATUS_FAILED
                    )
                if self.on_update:
                    self.on_update(self.alloc)
                return False
        return True

    def _on_task_state(self, task_name: str, state: TaskState) -> None:
        with self._lock:
            self.alloc.task_states[task_name] = state
            self._sync_client_status()
            all_dead = all(
                tr.state.state == TASK_STATE_DEAD
                for tr in self.task_runners.values()
            )
        # unmount only once every task is down (a failed sibling must
        # not rip the volume out from under still-running tasks), and
        # outside the lock — plugin RPCs can be slow
        if all_dead and self.csi_manager is not None:
            self.csi_manager.unmount_all(self.alloc.id)
        if self.on_update is not None:
            self.on_update(self.alloc)

    def _sync_client_status(self) -> None:
        """Derive the alloc's client status from task states
        (reference alloc_runner.go clientAlloc/getClientStatus)."""
        states = [tr.state for tr in self.task_runners.values()]
        if any(s.state == TASK_STATE_DEAD and s.failed for s in states):
            self.alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
        elif all(s.state == TASK_STATE_DEAD for s in states):
            self.alloc.client_status = ALLOC_CLIENT_STATUS_COMPLETE
        elif any(s.state == TASK_STATE_RUNNING for s in states):
            self.alloc.client_status = ALLOC_CLIENT_STATUS_RUNNING
        else:
            self.alloc.client_status = ALLOC_CLIENT_STATUS_PENDING

        # a leader task dying stops the rest (reference
        # alloc_runner.go handleTaskStateUpdates leader handling)
        leader_dead = any(
            tr.task.leader and tr.state.state == TASK_STATE_DEAD
            for tr in self.task_runners.values()
        )
        if leader_dead:
            for tr in self.task_runners.values():
                if not tr.task.leader:
                    tr.kill()

    # ------------------------------------------------------------------

    def destroy(self) -> None:
        with self._lock:
            self._destroyed = True
        for tr in self.task_runners.values():
            tr.kill()
        if self.csi_manager is not None:
            self.csi_manager.unmount_all(self.alloc.id)
        if self.device_manager is not None:
            self.device_manager.free(self.alloc.id)

    def wait(self, timeout: Optional[float] = None) -> bool:
        ok = True
        for tr in self.task_runners.values():
            ok = tr.wait(timeout) and ok
        return ok

    def is_terminal(self) -> bool:
        return self.alloc.client_terminal_status()

    def task_state_snapshot(self) -> Dict[str, Dict]:
        """Persistable view for client restarts
        (reference client/state/state_database.go)."""
        out = {}
        for name, tr in self.task_runners.items():
            snap = {
                "state": tr.state.state,
                "failed": tr.state.failed,
                "task_id": tr.task_id,
            }
            # driver-specific reattach metadata (e.g. the docker
            # container id) so recover_task has something to find
            hs = getattr(tr.driver, "handle_state", None)
            if hs is not None:
                try:
                    snap.update(hs(tr.task_id) or {})
                except Exception:  # noqa: BLE001
                    pass
            out[name] = snap
        return out
