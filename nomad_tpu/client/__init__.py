from .client import Client  # noqa: F401
from .alloc_runner import AllocRunner  # noqa: F401
from .task_runner import TaskRunner  # noqa: F401
