"""Client-side CSI: plugin clients and the volume mount manager.

Plays the role of the reference's CSI client stack:
`plugins/csi/` (the gRPC client talking to external CSI plugins, with
`plugins/csi/fake` for tests) and `client/pluginmanager/csimanager/`
(per-volume stage/publish orchestration + node fingerprinting).  The
plugin protocol here is an in-process interface rather than gRPC — the
seam is identical (probe / stage / publish / unpublish / unstage), so a
process-boundary client can slot in behind it.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class CSIPluginError(Exception):
    pass


class CSIPluginClient:
    """The node-plugin RPC surface (reference plugins/csi/client.go:
    NodeStageVolume/NodePublishVolume/... over gRPC)."""

    def probe(self) -> bool:
        raise NotImplementedError

    def node_stage_volume(
        self, volume_id: str, staging_path: str,
        access_mode: str, attachment_mode: str,
    ) -> None:
        raise NotImplementedError

    def node_publish_volume(
        self, volume_id: str, staging_path: str, target_path: str,
        read_only: bool,
    ) -> None:
        raise NotImplementedError

    def node_unpublish_volume(
        self, volume_id: str, target_path: str
    ) -> None:
        raise NotImplementedError

    def node_unstage_volume(
        self, volume_id: str, staging_path: str
    ) -> None:
        raise NotImplementedError


@dataclass
class FakeCSIPlugin(CSIPluginClient):
    """Scriptable plugin for tests (reference plugins/csi/fake):
    records every call and can inject failures per operation."""

    healthy: bool = True
    fail_stage: bool = False
    fail_publish: bool = False
    calls: List[Tuple[str, str]] = field(default_factory=list)
    staged: Dict[str, str] = field(default_factory=dict)
    published: Dict[str, str] = field(default_factory=dict)

    def probe(self) -> bool:
        self.calls.append(("probe", ""))
        return self.healthy

    def node_stage_volume(
        self, volume_id, staging_path, access_mode, attachment_mode
    ) -> None:
        self.calls.append(("stage", volume_id))
        if self.fail_stage:
            raise CSIPluginError(f"stage failed for {volume_id}")
        self.staged[volume_id] = staging_path

    def node_publish_volume(
        self, volume_id, staging_path, target_path, read_only
    ) -> None:
        self.calls.append(("publish", volume_id))
        if self.fail_publish:
            raise CSIPluginError(f"publish failed for {volume_id}")
        self.published[volume_id] = target_path

    def node_unpublish_volume(self, volume_id, target_path) -> None:
        self.calls.append(("unpublish", volume_id))
        self.published.pop(volume_id, None)

    def node_unstage_volume(self, volume_id, staging_path) -> None:
        self.calls.append(("unstage", volume_id))
        self.staged.pop(volume_id, None)


@dataclass
class MountInfo:
    volume_id: str
    plugin_id: str
    staging_path: str
    target_path: str


class CSIManager:
    """Stages/publishes CSI volumes for allocations and fingerprints
    plugin health onto the node (reference
    client/pluginmanager/csimanager/volume.go MountVolume)."""

    def __init__(
        self,
        data_dir: str = "",
        plugins: Optional[Dict[str, CSIPluginClient]] = None,
    ) -> None:
        self.data_dir = data_dir or "/tmp/nomad-tpu-csi"
        self.plugins: Dict[str, CSIPluginClient] = dict(plugins or {})
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # (alloc_id, volume_id) -> MountInfo
        self._mounts: Dict[Tuple[str, str], MountInfo] = {}
        # keys with a stage/publish in flight — plugin RPCs can be
        # slow, so they run outside the lock
        self._inflight: set = set()

    def fingerprint_node(self, node) -> None:
        """Publish plugin health into Node.csi_node_plugins (reference
        client/pluginmanager/csimanager/fingerprint.go)."""
        for pid, plugin in self.plugins.items():
            try:
                node.csi_node_plugins[pid] = bool(plugin.probe())
            except Exception:  # noqa: BLE001 — unhealthy on error
                node.csi_node_plugins[pid] = False

    def mount_volume(
        self,
        plugin_id: str,
        volume_id: str,
        alloc_id: str,
        read_only: bool,
        access_mode: str = "single-node-writer",
        attachment_mode: str = "file-system",
    ) -> MountInfo:
        plugin = self.plugins.get(plugin_id)
        if plugin is None:
            raise CSIPluginError(f"no CSI plugin {plugin_id!r} on node")
        staging = os.path.join(
            self.data_dir, "staging", plugin_id, volume_id
        )
        target = os.path.join(
            self.data_dir, "per-alloc", alloc_id, volume_id
        )
        key = (alloc_id, volume_id)
        with self._cond:
            while key in self._inflight:
                self._cond.wait()
            existing = self._mounts.get(key)
            if existing is not None:
                return existing
            self._inflight.add(key)
        try:
            plugin.node_stage_volume(
                volume_id, staging, access_mode, attachment_mode
            )
            plugin.node_publish_volume(
                volume_id, staging, target, read_only
            )
            info = MountInfo(volume_id, plugin_id, staging, target)
            with self._cond:
                self._mounts[key] = info
            return info
        finally:
            with self._cond:
                self._inflight.discard(key)
                self._cond.notify_all()

    def unmount_volume(self, volume_id: str, alloc_id: str) -> None:
        key = (alloc_id, volume_id)
        with self._cond:
            while key in self._inflight:
                self._cond.wait()
            info = self._mounts.pop(key, None)
            if info is None:
                return
            # decide about unstage while the table is consistent
            last_user = not any(
                vid == volume_id for (_a, vid) in self._mounts
            )
        plugin = self.plugins.get(info.plugin_id)
        if plugin is None:
            return
        try:
            plugin.node_unpublish_volume(volume_id, info.target_path)
        finally:
            if last_user:
                plugin.node_unstage_volume(volume_id, info.staging_path)

    def unmount_all(self, alloc_id: str) -> None:
        with self._cond:
            vols = [v for (a, v) in self._mounts if a == alloc_id]
        for v in vols:
            self.unmount_volume(v, alloc_id)

    def mounts_for_alloc(self, alloc_id: str) -> List[MountInfo]:
        with self._lock:
            return [
                info
                for (a, _v), info in self._mounts.items()
                if a == alloc_id
            ]
