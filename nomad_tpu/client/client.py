"""Client agent (reference client/client.go:309).

Registers the fingerprinted node, heartbeats, watches the server for
allocation changes (the in-process analog of the blocking
`Node.GetClientAllocs` query, node_endpoint.go:926), reconciles desired
vs running allocs (client.go:2183 runAllocs), runs them through
AllocRunners and pushes client-status updates back (`Node.UpdateAlloc`).

Local state is persisted as JSON under the data dir so a restarted client
restores its alloc runners (reference client/state/state_database.go +
Restore paths)."""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import replace as _replace
from typing import Dict, List, Optional

from ..structs import (
    ALLOC_DESIRED_STOP,
    Allocation,
    Node,
)
from .alloc_runner import AllocRunner
from .drivers import BUILTIN_DRIVERS, new_driver
from .fingerprint import fingerprint_drivers, run_fingerprinters


class Client:
    def __init__(
        self,
        server,
        node: Optional[Node] = None,
        data_dir: str = "",
        heartbeat_interval: float = 10.0,
        watch_interval: float = 0.05,
        drivers: Optional[List[str]] = None,
        fingerprint: bool = True,
        include_tpu_fingerprint: bool = False,
        secrets=None,
        csi_plugins=None,
    ) -> None:
        self.secrets = secrets
        self.server = server
        self.node = node or Node()
        self.data_dir = data_dir
        self.heartbeat_interval = heartbeat_interval
        self.watch_interval = watch_interval
        # a dict maps driver name -> instance (e.g. ExternalDriver
        # plugin processes); a list names builtin drivers
        if isinstance(drivers, dict):
            self.drivers = dict(drivers)
        else:
            self.drivers = {
                name: new_driver(name)
                for name in (drivers or list(BUILTIN_DRIVERS))
            }
        if fingerprint:
            run_fingerprinters(
                self.node, include_tpu=include_tpu_fingerprint
            )
        fingerprint_drivers(self.node, self.drivers)
        # device plugins (devices.py; reference client/devicemanager)
        from .devices import DeviceManager, TPUDevicePlugin

        self.device_manager = DeviceManager(
            plugins=(
                [TPUDevicePlugin()] if include_tpu_fingerprint else []
            )
        )
        self.device_manager.fingerprint_node(self.node)
        from .csi import CSIManager

        self.csi_manager = CSIManager(
            data_dir=os.path.join(data_dir, "csi") if data_dir else "",
            plugins=csi_plugins,
        )
        self.csi_manager.fingerprint_node(self.node)

        # alloc-dir GC (reference client/gc.go) + disconnect stopper
        # (reference client/heartbeatstop.go)
        from .gc import AllocGarbageCollector
        from .heartbeatstop import HeartbeatStopper

        self.gc = AllocGarbageCollector(
            alloc_base_dir=(
                os.path.join(data_dir, "allocs") if data_dir else ""
            ),
            destroy_fn=self._gc_destroy_alloc,
        )
        self.heartbeat_stopper = HeartbeatStopper(
            stop_alloc_fn=self._stop_alloc_local,
            # never fire between two healthy heartbeats: an alloc's
            # stop_after window can't be shorter than the time it takes
            # to learn the servers are really gone
            min_grace=2.0 * heartbeat_interval,
        )

        self.alloc_runners: Dict[str, AllocRunner] = {}
        self._known_alloc_index: Dict[str, int] = {}
        # reentrant: GC destroy callbacks fire under the watch loop's
        # critical section and need to mutate the runner map
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # periodic driver re-fingerprint cadence (reference
        # FingerprintManager); tests shrink it
        self.refingerprint_interval = 30.0
        self._fingerprint_dirty = False

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._restore()
        self.server.register_node(self.node)
        if hasattr(self.server, "register_client"):
            self.server.register_client(self.node.id, self)
        self._stop.clear()
        self.heartbeat_stopper.start()
        for target, name in (
            (self._heartbeat_loop, "client-heartbeat"),
            (self._watch_allocs_loop, "client-watch"),
            (self._check_loop, "client-checks"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.heartbeat_stopper.stop()
        for t in self._threads:
            t.join(timeout=2.0)
        for runner in self.alloc_runners.values():
            runner.destroy()
        self._persist()
        # external plugin drivers own subprocesses/sockets
        for driver in self.drivers.values():
            shutdown = getattr(driver, "shutdown", None)
            if callable(shutdown):
                try:
                    shutdown()
                except Exception:  # noqa: BLE001
                    pass

    # ------------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        last_refingerprint = time.monotonic()
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self.server.heartbeat(self.node.id)
                self.heartbeat_stopper.note_heartbeat_ok()
            except KeyError:
                self.server.register_node(self.node)
                self.heartbeat_stopper.note_heartbeat_ok()
            except Exception:  # noqa: BLE001
                # unreachable servers: the stopper's clock keeps aging
                pass
            # periodic driver re-fingerprint (reference
            # FingerprintManager runs fingerprinters on an interval
            # and diffs into node updates): a docker daemon that
            # starts or dies after agent boot flips the node's driver
            # attributes so placement follows reality
            now = time.monotonic()
            if now - last_refingerprint >= (
                self.refingerprint_interval
            ):
                last_refingerprint = now
                self._refingerprint_drivers()

    def _refingerprint_drivers(self) -> None:
        """One re-fingerprint cycle.  Per-driver isolation (a raising
        driver reads as dead, not as aborting the sweep), attribute
        REPLACEMENT for the driver.* namespace (a dead daemon's stale
        version keys don't linger), atomic dict swap (in-process
        readers share this Node by reference), a recomputed
        computed_class (class-keyed eligibility caches and blocked-
        eval unblocking must see the new shape), and a dirty flag so
        a failed register retries next cycle even when the attrs
        didn't change again."""
        from ..structs import compute_node_class

        new_attrs: Dict[str, str] = {}
        for name, driver in self.drivers.items():
            try:
                new_attrs.update(driver.fingerprint())
                new_attrs.setdefault(f"driver.{name}", "1")
            except Exception:  # noqa: BLE001
                new_attrs[f"driver.{name}"] = "0"
        old_attrs = {
            k: v
            for k, v in self.node.attributes.items()
            if k.startswith("driver.")
        }
        if new_attrs == old_attrs and not self._fingerprint_dirty:
            return
        merged = {
            k: v
            for k, v in self.node.attributes.items()
            if not k.startswith("driver.")
        }
        merged.update(new_attrs)
        # single reference assignment: concurrent readers iterate
        # either the old or the new dict, never a mutating one
        self.node.attributes = merged
        for name in self.drivers:
            self.node.drivers[name] = (
                new_attrs.get(f"driver.{name}") == "1"
            )
        self.node.computed_class = compute_node_class(self.node)
        try:
            self.server.register_node(self.node)
            self._fingerprint_dirty = False
        except Exception:  # noqa: BLE001
            # delivery failed: retry next cycle even if nothing
            # changes again (the local dict already holds the truth)
            self._fingerprint_dirty = True

    def _stop_alloc_local(self, alloc_id: str) -> None:
        """Kill an alloc locally after server contact loss exceeds its
        stop_after_client_disconnect (heartbeatstop.go)."""
        with self._lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is not None:
            runner.destroy()

    def _gc_destroy_alloc(self, alloc_id: str) -> None:
        """GC callback: tear down the runner (if any) and its dir."""
        from .allocdir import AllocDir

        with self._lock:
            runner = self.alloc_runners.pop(alloc_id, None)
            self._known_alloc_index.pop(alloc_id, None)
        if runner is not None:
            runner.destroy()
        if self.data_dir:
            ad = getattr(runner, "alloc_dir_obj", None) or AllocDir(
                os.path.join(self.data_dir, "allocs"), alloc_id
            )
            ad.destroy()

    def _watch_allocs_loop(self) -> None:
        """(reference client.go:1961 watchAllocations)"""
        while not self._stop.wait(self.watch_interval):
            try:
                self._run_allocs()
            except Exception:  # noqa: BLE001
                pass

    def _run_allocs(self) -> None:
        """Diff server-desired allocs against running runners
        (reference client.go:2183 runAllocs)."""
        server_allocs = {
            a.id: a
            for a in self.server.store.allocs_by_node(self.node.id)
        }
        # the remote store's watch call long-polls (up to ~20s), so a
        # stopped client's parked poll can resolve AFTER stop()
        # destroyed the runners and persisted state — acting on the
        # result then would spawn orphaned tasks on a dead client
        if self._stop.is_set():
            return
        with self._lock:
            # removals / stops
            for alloc_id, runner in list(self.alloc_runners.items()):
                desired = server_allocs.get(alloc_id)
                if desired is None or desired.desired_status in (
                    ALLOC_DESIRED_STOP,
                    "evict",
                ):
                    runner.destroy()
                    if desired is None:
                        del self.alloc_runners[alloc_id]
                        self._known_alloc_index.pop(alloc_id, None)
            # additions
            for alloc_id, alloc in server_allocs.items():
                if alloc.terminal_status():
                    continue
                if alloc_id in self.alloc_runners:
                    continue
                # detach from the store's canonical object (shared in
                # single-binary mode): the runner writes client_status
                # and task_states in place, and an in-place
                # live->terminal write would defeat the upsert's
                # was_live bookkeeping — the node would never free the
                # completed alloc's capacity
                alloc = _replace(
                    alloc, task_states=dict(alloc.task_states)
                )
                if alloc.job is None:
                    alloc.job = self.server.store.job_by_id(
                        alloc.namespace, alloc.job_id
                    )
                if alloc.job is None:
                    continue
                # GC room + previous-alloc watcher (allocwatcher.py);
                # the predecessor is exempt from GC until its sticky
                # data has a chance to migrate
                self.gc.make_room_for(
                    1,
                    exclude=(
                        {alloc.previous_allocation}
                        if alloc.previous_allocation
                        else None
                    ),
                )
                from .allocwatcher import watcher_for_alloc

                prev_watcher = watcher_for_alloc(
                    alloc,
                    self.alloc_runners,
                    alloc_base_dir=(
                        os.path.join(self.data_dir, "allocs")
                        if self.data_dir
                        else ""
                    ),
                    poll_terminal=self._alloc_terminal_on_server,
                )
                # pin the predecessor until migration has had its shot
                if alloc.previous_allocation:
                    prev_id = alloc.previous_allocation
                    self.gc.protect(prev_id)
                    prev_watcher.on_done = (
                        lambda pid=prev_id: self.gc.unprotect(pid)
                    )
                runner = AllocRunner(
                    alloc,
                    data_dir=self.data_dir,
                    on_update=self._push_alloc_update,
                    drivers=self.drivers,
                    secrets=self.secrets,
                    catalog=getattr(self.server, "catalog", None),
                    csi_manager=self.csi_manager,
                    csi_resolver=lambda ns, vid: (
                        self.server.store.csi_volume_by_id(ns, vid)
                    ),
                    node=self.node,
                    prev_watcher=prev_watcher,
                    device_manager=self.device_manager,
                )
                self.alloc_runners[alloc_id] = runner
                self.heartbeat_stopper.allocation_hook(alloc)
                runner.run()
            # feed the GC: terminal runners + live count
            live = 0
            for alloc_id, runner in self.alloc_runners.items():
                if runner.is_terminal():
                    self.gc.mark_terminal(alloc_id)
                else:
                    live += 1
            self.gc.set_live_count(live)
        self._persist()

    def _alloc_terminal_on_server(self, alloc_id: str) -> bool:
        a = self.server.store.alloc_by_id(alloc_id)
        return a is None or a.terminal_status()

    def _push_alloc_update(self, alloc: Allocation) -> None:
        """(reference client.go allocSync -> Node.UpdateAlloc)"""
        update = _replace(alloc)
        update.job = None
        update.modify_time = time.time()
        # rebind the full job on the server side
        update.job = self.server.store.job_by_id(
            alloc.namespace, alloc.job_id
        )
        self.server.update_allocs_from_client([update])

    def _check_loop(self) -> None:
        """Evaluate tcp/http service checks for running allocs and feed
        results to the catalog (reference command/agent/consul checks +
        client check watcher)."""
        import socket as _socket
        import urllib.request as _urlreq

        while not self._stop.wait(2.0):
            catalog = getattr(self.server, "catalog", None)
            if catalog is None:
                continue
            with self._lock:
                runners = list(self.alloc_runners.values())
            for runner in runners:
                if runner.is_terminal():
                    continue
                for tr in runner.task_runners.values():
                    for service in tr.task.services:
                        for check in service.checks:
                            passing = self._run_check(
                                check, runner.alloc, _socket, _urlreq
                            )
                            if passing is None:
                                continue
                            catalog.set_check_status(
                                runner.alloc.id,
                                tr.task.name,
                                service.name,
                                passing,
                            )

    @staticmethod
    def _run_check(check, alloc, _socket, _urlreq):
        ctype = check.get("type")
        if ctype == "tcp":
            address = check.get("address", "127.0.0.1")
            port = int(check.get("port", 0))
            if not port:
                return None
            try:
                with _socket.create_connection(
                    (address, port), timeout=1.0
                ):
                    return True
            except OSError:
                return False
        if ctype == "http":
            url = check.get("url") or check.get("path", "")
            if not url.startswith("http"):
                return None
            try:
                with _urlreq.urlopen(url, timeout=2.0) as resp:
                    return 200 <= resp.status < 300
            except Exception:  # noqa: BLE001
                return False
        return None

    # ------------------------------------------------------------------
    # local persistence (reference client/state/)

    def _state_path(self) -> Optional[str]:
        if not self.data_dir:
            return None
        return os.path.join(self.data_dir, "client_state.json")

    def _persist(self) -> None:
        path = self._state_path()
        if path is None:
            return
        with self._lock:
            state = {
                "node_id": self.node.id,
                "allocs": {
                    alloc_id: runner.task_state_snapshot()
                    for alloc_id, runner in self.alloc_runners.items()
                },
            }
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, path)

    def _restore(self) -> None:
        path = self._state_path()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                state = json.load(f)
        except (OSError, ValueError):
            return
        if state.get("node_id"):
            self.node.id = state["node_id"]
        # task reattachment: ask each driver to recover; unrecovered
        # tasks will be restarted by the watch loop on the next diff
        for alloc_id, tasks in state.get("allocs", {}).items():
            for task_name, snap in tasks.items():
                for driver in self.drivers.values():
                    if driver.recover_task(snap.get("task_id", ""), snap):
                        break

    # ------------------------------------------------------------------

    def restart_alloc(self, alloc_id: str, task: str = "") -> None:
        """Restart one task or every task of an alloc in place
        (reference client/allocrunner Restart; the task runner's
        restart loop picks the process back up)."""
        with self._lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is None:
            raise KeyError(alloc_id)
        if task and task not in runner.task_runners:
            raise KeyError(f"unknown task {task!r}")
        for name, tr in runner.task_runners.items():
            if task and name != task:
                continue
            tr.restart()

    def signal_alloc(
        self, alloc_id: str, signal: str = "SIGTERM", task: str = ""
    ) -> None:
        """(reference client/allocrunner Signal)"""
        with self._lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is None:
            raise KeyError(alloc_id)
        if task and task not in runner.task_runners:
            raise KeyError(f"unknown task {task!r}")
        for name, tr in runner.task_runners.items():
            if task and name != task:
                continue
            try:
                tr.driver.signal_task(tr.task_id, signal)
            except NotImplementedError:
                pass

    def exec_alloc(
        self,
        alloc_id: str,
        task: str,
        argv: List[str],
        timeout: float = 30.0,
    ):
        """Run a command in a task's context (reference
        client_alloc_endpoint.go Allocations.Exec backing
        `nomad alloc exec`).  Returns (exit_code, output_bytes)."""
        tr, env, cwd = self._task_exec_context(alloc_id, task)
        return tr.driver.exec_task(
            tr.task_id, argv, timeout=timeout, env=env, cwd=cwd
        )

    def exec_alloc_stream(self, alloc_id: str, task: str, argv):
        """Interactive exec handle in a task's context (reference
        Allocations.Exec streaming — backs `alloc exec -i` over the
        websocket transport)."""
        tr, env, cwd = self._task_exec_context(alloc_id, task)
        return tr.driver.exec_task_stream(
            tr.task_id, argv, env=env, cwd=cwd
        )

    def _task_exec_context(self, alloc_id: str, task: str):
        """(task runner, env, cwd) shared by the one-shot and
        streaming exec paths."""
        with self._lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is None:
            raise KeyError(alloc_id)
        tr = runner.task_runners.get(task)
        if tr is None:
            raise KeyError(f"unknown task {task!r}")
        env = tr.task_env.all() if tr.task_env is not None else dict(
            tr.env
        )
        cwd = tr.task_dir.local_dir if tr.task_dir is not None else ""
        return tr, env, cwd

    def read_task_log(
        self, alloc_id: str, task: str, kind: str = "stdout",
        max_bytes: int = 64 * 1024,
    ) -> bytes:
        """Last ``max_bytes`` of a task log from THIS client's disk
        (rotated logmon layout first, flat legacy second) — the
        non-follow read the server-side proxy forwards for
        `alloc logs` on remote clients."""
        import os as _os

        from .logmon import read_task_log as _read_rotated

        if not self.data_dir:
            raise KeyError("client has no data dir")
        # no existence check: the alloc dir appears moments after
        # placement, and the in-process proxy semantics have always
        # been "empty until the task writes" (callers poll)
        root = _os.path.join(self.data_dir, "allocs", alloc_id)
        data = _read_rotated(
            _os.path.join(root, "alloc", "logs"), task, kind,
            max_bytes,
        )
        if data:
            return data
        path = _os.path.join(root, f"{task}.{kind}")
        try:
            with open(path, "rb") as f:
                f.seek(0, _os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read()
        except OSError:
            return b""

    def tail_task_log(
        self, alloc_id: str, task: str, kind: str, cursor
    ):
        """One `logs -f` follow step: (appended bytes, new cursor)."""
        import os as _os

        from .logmon import follow_task_log

        root = self._alloc_fs_root(alloc_id)
        log_dir = _os.path.join(root, "alloc", "logs")
        flat = _os.path.join(root, f"{task}.{kind}")
        return follow_task_log(
            log_dir, task, kind, cursor, flat_path=flat
        )

    def _alloc_fs_root(self, alloc_id: str) -> str:
        if not self.data_dir:
            raise KeyError("client has no data dir")
        root = os.path.join(self.data_dir, "allocs", alloc_id)
        if not os.path.isdir(root):
            raise KeyError(alloc_id)
        return root

    def _alloc_fs_resolve(self, alloc_id: str, rel: str) -> str:
        """Containment check shared by ls/cat (reference client fs
        endpoints refuse to escape the alloc dir)."""
        from .getter import contained_path

        return contained_path(self._alloc_fs_root(alloc_id), rel)

    def list_alloc_files(self, alloc_id: str, rel: str = ""):
        """(reference client fs ls endpoint)"""
        base = self._alloc_fs_resolve(alloc_id, rel)
        if not os.path.isdir(base):
            raise KeyError(rel)
        out = []
        for name in sorted(os.listdir(base)):
            full = os.path.join(base, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            out.append(
                {
                    "Name": name,
                    "IsDir": os.path.isdir(full),
                    "Size": st.st_size,
                    "ModTime": st.st_mtime,
                }
            )
        return out

    def read_alloc_file(
        self, alloc_id: str, rel: str, max_bytes: int = 256 * 1024
    ):
        """(reference client fs cat/readat endpoints)
        Returns (data, truncated)."""
        path = self._alloc_fs_resolve(alloc_id, rel)
        if not os.path.isfile(path):
            raise KeyError(rel)
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            return f.read(max_bytes), size > max_bytes

    def running_allocs(self) -> List[str]:
        with self._lock:
            return [
                alloc_id
                for alloc_id, r in self.alloc_runners.items()
                if not r.is_terminal()
            ]
