"""Task template rendering + secret access.

The reference integrates consul-template (task template hook renders
files with Consul keys / Vault secrets before start, re-rendering on
change) and derives Vault tokens server-side (nomad/vault.go).  The
nomad-tpu analogs:

* `SecretsProvider` — the secret-backend seam.  `StaticSecretsProvider`
  (in-memory) and `FileSecretsProvider` (directory of JSON documents,
  the "dev server" shape) ship in-tree; a real Vault client can slot in
  behind the same two methods.
* `render_template` — the template dialect: `{{ env "NAME" }}`,
  `{{ meta "key" }}`, `{{ secret "path" "field" }}`,
  `{{ key "path" }}` (whole secret document as JSON) and
  `{{ service "name" }}` (comma-joined healthy `addr:port` list from the
  service catalog).
* The task-runner template hook writes rendered files into the alloc
  dir before the driver starts (reference taskrunner/template/).
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Protocol


class SecretsProvider(Protocol):
    def read(self, path: str) -> Optional[Dict[str, Any]]:
        ...


class StaticSecretsProvider:
    def __init__(self, secrets: Optional[Dict[str, Dict]] = None) -> None:
        self.secrets = secrets or {}

    def read(self, path: str) -> Optional[Dict[str, Any]]:
        return self.secrets.get(path)


class FileSecretsProvider:
    """Secrets as JSON files under a root directory: secret path a/b/c
    maps to <root>/a/b/c.json."""

    def __init__(self, root: str) -> None:
        self.root = root

    def read(self, path: str) -> Optional[Dict[str, Any]]:
        safe = os.path.normpath(path).lstrip("/")
        if safe.startswith(".."):
            return None
        full = os.path.join(self.root, safe + ".json")
        try:
            with open(full) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


_TEMPLATE_RE = re.compile(
    r"\{\{\s*(env|meta|secret|key|service)\s+((?:\"[^\"]*\"\s*)+)\}\}"
)


class TemplateError(Exception):
    pass


def render_template(
    text: str,
    env: Optional[Dict[str, str]] = None,
    meta: Optional[Dict[str, str]] = None,
    secrets: Optional[SecretsProvider] = None,
    catalog=None,
) -> str:
    env = env or {}
    meta = meta or {}

    def sub(match: re.Match) -> str:
        fn = match.group(1)
        args = re.findall(r"\"([^\"]*)\"", match.group(2))
        if fn == "env":
            return env.get(args[0], "")
        if fn == "meta":
            return meta.get(args[0], "")
        if fn == "secret":
            if secrets is None:
                raise TemplateError("no secrets provider configured")
            doc = secrets.read(args[0])
            if doc is None:
                raise TemplateError(f"unknown secret {args[0]!r}")
            if len(args) > 1:
                if args[1] not in doc:
                    raise TemplateError(
                        f"secret {args[0]!r} has no field {args[1]!r}"
                    )
                return str(doc[args[1]])
            return json.dumps(doc)
        if fn == "key":
            if secrets is None:
                raise TemplateError("no secrets provider configured")
            doc = secrets.read(args[0])
            return json.dumps(doc) if doc is not None else ""
        if fn == "service":
            if catalog is None:
                return ""
            instances = catalog.instances(args[0], healthy_only=True)
            return ",".join(
                f"{i.address or 'localhost'}:{i.port}"
                for i in instances
            )
        raise TemplateError(f"unknown template function {fn!r}")

    return _TEMPLATE_RE.sub(sub, text)


def render_task_templates(
    templates: List[Dict[str, Any]],
    alloc_dir: str,
    env: Dict[str, str],
    meta: Dict[str, str],
    secrets: Optional[SecretsProvider],
    catalog=None,
) -> List[str]:
    """Render a task's template blocks into the alloc dir; returns the
    written paths.  Template block shape: {"destination": "local/x.conf",
    "data": "..."} (reference structs.go Template)."""
    written = []
    for template in templates:
        destination = template.get("destination", "")
        data = template.get("data", "")
        if not destination:
            continue
        rendered = render_template(
            data, env=env, meta=meta, secrets=secrets, catalog=catalog
        )
        path = os.path.join(alloc_dir, destination)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(rendered)
        written.append(path)
    return written
