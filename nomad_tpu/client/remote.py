"""Remote client agent plumbing: the client runtime attached to a
networked cluster over the HTTP surface, mirroring the reference's
client<->server topology (client/rpc.go: clients dial servers for
registration/heartbeats/alloc sync; servers reach BACK through the
client's own endpoint for fs/exec/logs — reference
client/agent_endpoint.go + nomad/client_rpc.go NodeRpc).

Three pieces:

* :class:`RemoteServer` — what the in-process ``Client`` sees as its
  "server": registration, heartbeats and alloc-status pushes become
  HTTP calls with failover across the configured server addresses
  (writes forward follower->leader server-side), and ``.store`` is a
  :class:`RemoteStore` decoding the /v1 read surface back into
  structs.
* :class:`ClientEndpoint` — a small HTTP server ON the client that
  exposes the server->client callback surface (restart/signal/exec/
  log-tail/ls/cat) against the local ``Client`` object.
* the server side registers an :class:`~nomad_tpu.api.http`
  ``HTTPClientProxy`` for the node when the client announces its
  callback address via POST /v1/client/register.
"""
from __future__ import annotations

import base64
import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..api.codec import (
    alloc_from_dict,
    alloc_to_dict,
    csi_volume_from_dict,
    job_from_dict,
    node_to_dict,
)


def _req(base: str, method: str, path: str, body=None,
         timeout=10.0, with_index=False):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        base + path, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
        idx = resp.headers.get("X-Nomad-Index") if with_index else None
    payload = json.loads(raw or b"null")
    if with_index:
        return payload, (int(idx) if idx else 0)
    return payload


class RemoteStore:
    """Read-side proxy over /v1 for the client runtime.  Decodes the
    snake_case wire forms back into structs; reads hit the first
    reachable server (reads are locally served on any server; the
    client tolerates follower lag exactly like the reference's
    stale-read node paths)."""

    def __init__(self, remote: "RemoteServer") -> None:
        self._remote = remote
        # blocking-query cursor for the alloc watch (reference
        # client.go watchAllocations rides blocking queries too): a
        # long-poll with ?index=N&wait returns immediately on change
        # and parks server-side otherwise — the client's 2/s tight
        # poll becomes a handful of idle requests per minute
        self._allocs_index = 0

    def allocs_by_node(self, node_id: str):
        path = f"/v1/node/{node_id}/allocations"
        if self._allocs_index:
            path += f"?index={self._allocs_index}&wait=10"
        # same transport as every other call: failover on
        # connectivity, HTTPError is a real answer (no failover).
        # Raft indexes are identical across replicas, so the cursor
        # survives a server switch — a lagging follower just parks
        # the poll until it catches up.
        raw, idx = self._remote._call(
            "GET", path, timeout=20.0, with_index=True
        )
        if idx:
            self._allocs_index = idx
        return [alloc_from_dict(a) for a in raw or []]

    def alloc_by_id(self, alloc_id: str):
        try:
            raw = self._remote._call(
                "GET", f"/v1/allocation/{alloc_id}"
            )
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise
        return alloc_from_dict(raw) if raw else None

    def job_by_id(self, namespace: str, job_id: str):
        try:
            raw = self._remote._call(
                "GET", f"/v1/job/{job_id}?namespace={namespace}"
            )
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise
        return job_from_dict(raw) if raw else None

    def csi_volume_by_id(self, namespace: str, vol_id: str):
        try:
            raw = self._remote._call(
                "GET",
                f"/v1/volume/csi/{vol_id}?namespace={namespace}",
            )
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise
        return csi_volume_from_dict(raw) if raw else None


class RemoteServer:
    """The ``Client``'s server handle against a networked cluster.

    Every call tries the configured servers in order and sticks with
    the last one that answered (reference client/servers manager);
    writes landing on a follower forward to the leader server-side."""

    def __init__(self, servers: List[str],
                 callback_host: str = "127.0.0.1") -> None:
        self.servers = [s.rstrip("/") for s in servers]
        self._preferred = 0
        self.callback_host = callback_host
        self._endpoint: Optional[ClientEndpoint] = None
        self._announced_node = ""
        self._last_announce = 0.0
        self.catalog = None

        self.store = RemoteStore(self)

    # -- transport -----------------------------------------------------

    def _call(self, method: str, path: str, body=None,
              timeout=10.0, with_index=False):
        last: Optional[Exception] = None
        n = len(self.servers)
        for k in range(n):
            i = (self._preferred + k) % n
            try:
                out = _req(
                    self.servers[i], method, path, body,
                    timeout=timeout, with_index=with_index,
                )
                self._preferred = i
                return out
            except urllib.error.HTTPError:
                # the server answered: HTTP errors are REAL answers
                # (404 etc.), not connectivity — don't failover
                self._preferred = i
                raise
            except Exception as exc:  # noqa: BLE001
                last = exc
        raise ConnectionError(
            f"no server reachable: {last!r}"
        )

    # -- the surface Client uses ---------------------------------------

    def register_node(self, node) -> None:
        self._call(
            "POST", "/v1/node/register",
            {"Node": node_to_dict(node)},
        )

    def heartbeat(self, node_id: str) -> None:
        try:
            self._call(
                "POST", f"/v1/node/{node_id}/heartbeat", {}
            )
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                # unknown node: surface the in-process contract
                # (KeyError) so Client._heartbeat_loop re-registers
                # instead of heartbeating into 404s forever
                raise KeyError(node_id)
            raise
        # the callback registry is per-server-process MEMORY: a
        # server restarted after our Client.start() has no proxy for
        # this node until we re-announce.  Piggyback on the heartbeat
        # cadence, cheaply.
        import time as _time

        if (
            self._endpoint is not None
            and self._announced_node
            and _time.monotonic() - self._last_announce > 30.0
        ):
            try:
                self.register_client(self._announced_node, None)
            except Exception:  # noqa: BLE001 — next beat retries
                pass

    def update_allocs_from_client(self, updates) -> None:
        if not updates:
            return
        node_id = updates[0].node_id
        self._call(
            "POST", f"/v1/node/{node_id}/allocs",
            {"Allocs": [alloc_to_dict(a) for a in updates]},
        )

    def register_client(self, node_id: str, client) -> None:
        """Start the callback endpoint and announce its address so
        the servers can proxy fs/exec/logs to this client.  The
        registry is per-server-process memory (not raft state), so
        the announcement goes to EVERY configured server best-effort
        — any of them may serve an fs/exec request for this node."""
        if self._endpoint is None:
            self._endpoint = ClientEndpoint(
                client, host=self.callback_host
            )
            self._endpoint.start()
        import time as _time

        self._announced_node = node_id
        self._last_announce = _time.monotonic()
        body = {
            "NodeID": node_id,
            "Addr": (
                f"http://{self.callback_host}:"
                f"{self._endpoint.port}"
            ),
        }
        ok = 0
        for base in self.servers:
            try:
                _req(base, "POST", "/v1/client/register", body)
                ok += 1
            except Exception:  # noqa: BLE001
                continue
        if not ok:
            raise ConnectionError(
                "no server accepted the client registration"
            )

    def stop(self) -> None:
        if self._endpoint is not None:
            self._endpoint.stop()
            self._endpoint = None


class ClientEndpoint:
    """The client's own HTTP surface: what the servers call to reach
    allocs on this node (reference client/agent_endpoint.go)."""

    def __init__(self, client, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.client = client
        self.host = host
        self._httpd = ThreadingHTTPServer(
            (host, port), self._make_handler()
        )
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="client-endpoint",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def _make_handler(self):
        client = self.client

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header(
                    "Content-Type", "application/json"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self):
                try:
                    body = self._body()
                    if self.path == "/restart":
                        client.restart_alloc(
                            body["alloc_id"], body.get("task", "")
                        )
                        return self._json({})
                    if self.path == "/signal":
                        client.signal_alloc(
                            body["alloc_id"],
                            body.get("signal", "SIGTERM"),
                            body.get("task", ""),
                        )
                        return self._json({})
                    if self.path == "/exec":
                        rc, out = client.exec_alloc(
                            body["alloc_id"],
                            body.get("task", ""),
                            body.get("argv") or [],
                            float(body.get("timeout", 30.0)),
                        )
                        return self._json(
                            {
                                "rc": rc,
                                "output": base64.b64encode(
                                    out
                                ).decode(),
                            }
                        )
                    if self.path == "/logs-tail":
                        cursor = body.get("cursor")
                        data, cur = client.tail_task_log(
                            body["alloc_id"],
                            body.get("task", ""),
                            body.get("kind", "stdout"),
                            tuple(cursor) if cursor else None,
                        )
                        return self._json(
                            {
                                "data": base64.b64encode(
                                    data
                                ).decode(),
                                "cursor": list(cur),
                            }
                        )
                    if self.path == "/read-task-log":
                        data = client.read_task_log(
                            body["alloc_id"],
                            body.get("task", ""),
                            body.get("kind", "stdout"),
                            int(body.get("max_bytes", 65536)),
                        )
                        return self._json(
                            {
                                "data": base64.b64encode(
                                    data
                                ).decode()
                            }
                        )
                    if self.path == "/ls":
                        return self._json(
                            client.list_alloc_files(
                                body["alloc_id"],
                                body.get("path", ""),
                            )
                        )
                    if self.path == "/cat":
                        data, trunc = client.read_alloc_file(
                            body["alloc_id"], body.get("path", "")
                        )
                        return self._json(
                            {
                                "data": base64.b64encode(
                                    data
                                ).decode(),
                                "truncated": trunc,
                            }
                        )
                    return self._json(
                        {"error": "not found"}, code=404
                    )
                except KeyError as exc:
                    return self._json(
                        {"error": str(exc)}, code=404
                    )
                except Exception as exc:  # noqa: BLE001
                    return self._json(
                        {"error": repr(exc)}, code=500
                    )

        return Handler


class HTTPClientProxy:
    """Server-side handle to a REMOTE client: implements the same
    surface an in-process ``Client`` registers, forwarding each call
    to the client's callback endpoint (reference nomad/client_rpc.go
    NodeRpc)."""

    def __init__(self, addr: str) -> None:
        self.addr = addr.rstrip("/")

    def _post(self, path: str, body):
        try:
            return _req(self.addr, "POST", path, body)
        except urllib.error.HTTPError as exc:
            detail = ""
            try:
                detail = json.loads(exc.read()).get("error", "")
            except Exception:  # noqa: BLE001
                pass
            if exc.code == 404:
                raise KeyError(detail or "not found")
            raise RuntimeError(detail or str(exc))

    def restart_alloc(self, alloc_id: str, task: str = "") -> None:
        self._post(
            "/restart", {"alloc_id": alloc_id, "task": task}
        )

    def signal_alloc(
        self, alloc_id: str, signal: str = "SIGTERM",
        task: str = "",
    ) -> None:
        self._post(
            "/signal",
            {"alloc_id": alloc_id, "signal": signal, "task": task},
        )

    def exec_alloc(
        self, alloc_id: str, task: str, argv, timeout: float = 30.0
    ):
        out = self._post(
            "/exec",
            {
                "alloc_id": alloc_id,
                "task": task,
                "argv": list(argv),
                "timeout": timeout,
            },
        )
        return out["rc"], base64.b64decode(out["output"])

    def exec_alloc_stream(self, alloc_id: str, task: str, argv):
        raise KeyError(
            "interactive exec requires a direct client connection"
        )

    def tail_task_log(
        self, alloc_id: str, task: str, kind: str, cursor
    ):
        out = self._post(
            "/logs-tail",
            {
                "alloc_id": alloc_id,
                "task": task,
                "kind": kind,
                "cursor": list(cursor) if cursor else None,
            },
        )
        return base64.b64decode(out["data"]), tuple(out["cursor"])

    def read_task_log(
        self, alloc_id: str, task: str, kind: str = "stdout",
        max_bytes: int = 64 * 1024,
    ) -> bytes:
        out = self._post(
            "/read-task-log",
            {
                "alloc_id": alloc_id,
                "task": task,
                "kind": kind,
                "max_bytes": max_bytes,
            },
        )
        return base64.b64decode(out["data"])

    def list_alloc_files(self, alloc_id: str, rel: str = ""):
        return self._post(
            "/ls", {"alloc_id": alloc_id, "path": rel}
        )

    def read_alloc_file(self, alloc_id: str, rel: str):
        out = self._post(
            "/cat", {"alloc_id": alloc_id, "path": rel}
        )
        return base64.b64decode(out["data"]), out["truncated"]
