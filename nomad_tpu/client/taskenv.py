"""Task environment construction + interpolation
(reference client/taskenv/env.go).

Builds the full ``NOMAD_*`` environment a task sees and interpolates
``${...}`` references in arbitrary strings (task config values, template
bodies, service names) against that environment plus node attributes —
the client-side counterpart of the scheduler's constraint target
resolution (reference client/taskenv/env.go:ParseAndReplace).
"""
from __future__ import annotations

import re
from typing import Dict, Optional

_VAR_RE = re.compile(r"\$\{([^}]+)\}")


def _clean(name: str) -> str:
    """Env-var-safe key (reference helper/envvars)."""
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


class TaskEnv:
    """Immutable resolved environment (reference taskenv.TaskEnv)."""

    def __init__(self, env: Dict[str, str], node_attrs: Dict[str, str]):
        self.env = env
        self.node_attrs = node_attrs

    def all(self) -> Dict[str, str]:
        return dict(self.env)

    def replace(self, s: str) -> str:
        """Interpolate ``${...}`` occurrences.  Recognized forms:
        ``${NOMAD_*}`` / ``${env.X}`` (the task env), ``${node.*}`` /
        ``${attr.*}`` / ``${meta.*}`` (node attributes, same namespace
        as scheduler constraints, feasible.go:713 resolveTarget).
        Unknown references resolve to the empty string, matching the
        reference's behavior for missing attributes."""

        def sub(m: re.Match) -> str:
            key = m.group(1).strip()
            if key.startswith("env."):
                return self.env.get(key[4:], "")
            if (
                key.startswith("node.")
                or key.startswith("attr.")
                or key.startswith("meta.")
            ):
                return self.node_attrs.get(key, "")
            return self.env.get(key, "")

        return _VAR_RE.sub(sub, s)

    def replace_all(self, obj):
        """Deep-interpolate strings in dict/list/str config trees."""
        if isinstance(obj, str):
            return self.replace(obj)
        if isinstance(obj, dict):
            return {k: self.replace_all(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self.replace_all(v) for v in obj]
        return obj


class Builder:
    """Assembles a TaskEnv from alloc/task/node context
    (reference taskenv.Builder; setters mirror setAlloc/setTask/setNode).
    """

    def __init__(self) -> None:
        self.env: Dict[str, str] = {}
        self.node_attrs: Dict[str, str] = {}

    # ------------------------------------------------------------------

    def set_alloc(self, alloc, job=None, tg=None) -> "Builder":
        job = job or alloc.job
        self.env["NOMAD_ALLOC_ID"] = alloc.id
        self.env["NOMAD_SHORT_ALLOC_ID"] = alloc.id[:8]
        self.env["NOMAD_ALLOC_NAME"] = alloc.name
        self.env["NOMAD_ALLOC_INDEX"] = str(alloc.index())
        self.env["NOMAD_GROUP_NAME"] = alloc.task_group
        self.env["NOMAD_NAMESPACE"] = alloc.namespace
        if job is not None:
            self.env["NOMAD_JOB_ID"] = job.id
            self.env["NOMAD_JOB_NAME"] = job.name
            if job.parent_id:
                self.env["NOMAD_JOB_PARENT_ID"] = job.parent_id
            tg = tg or job.lookup_task_group(alloc.task_group)
            # job < group < task meta precedence, NOMAD_META_<key> forms
            meta = dict(job.meta)
            if tg is not None:
                meta.update(tg.meta)
            self._set_meta(meta)
        return self

    def set_task(self, task, task_dir=None) -> "Builder":
        self.env["NOMAD_TASK_NAME"] = task.name
        if task.resources is not None:
            self.env["NOMAD_CPU_LIMIT"] = str(task.resources.cpu)
            self.env["NOMAD_MEMORY_LIMIT"] = str(task.resources.memory_mb)
            self._set_networks(task.resources.networks)
        self._set_meta(task.meta)
        for k, v in task.env.items():
            self.env[k] = v
        if task_dir is not None:
            self.env["NOMAD_ALLOC_DIR"] = task_dir.shared_alloc_dir
            self.env["NOMAD_TASK_DIR"] = task_dir.local_dir
            self.env["NOMAD_SECRETS_DIR"] = task_dir.secrets_dir
        return self

    def set_node(self, node, region: str = "global") -> "Builder":
        self.env["NOMAD_DC"] = node.datacenter
        self.env["NOMAD_REGION"] = region
        # constraint-style namespace (feasible.go resolveTarget)
        self.node_attrs["node.unique.id"] = node.id
        self.node_attrs["node.unique.name"] = node.name
        self.node_attrs["node.datacenter"] = node.datacenter
        self.node_attrs["node.region"] = region
        self.node_attrs["node.class"] = node.node_class
        for k, v in node.attributes.items():
            self.node_attrs[f"attr.{k}"] = str(v)
        for k, v in node.meta.items():
            self.node_attrs[f"meta.{k}"] = str(v)
        return self

    def set_ports(self, port_map: Dict[str, int], ip: str = "127.0.0.1"):
        """Explicit port assignments (post-placement NetworkIndex offer:
        structs/network.py) → NOMAD_{ADDR,IP,HOST_PORT,PORT}_<label>."""
        for label, port in port_map.items():
            lab = _clean(label)
            self.env[f"NOMAD_IP_{lab}"] = ip
            self.env[f"NOMAD_PORT_{lab}"] = str(port)
            self.env[f"NOMAD_HOST_PORT_{lab}"] = str(port)
            self.env[f"NOMAD_ADDR_{lab}"] = f"{ip}:{port}"
        return self

    def set_vault_token(self, token: str) -> "Builder":
        if token:
            self.env["VAULT_TOKEN"] = token
        return self

    # ------------------------------------------------------------------

    def _set_networks(self, networks) -> None:
        for net in networks:
            ip = net.ip or "127.0.0.1"
            for port in list(net.reserved_ports) + list(net.dynamic_ports):
                if not port.label:
                    continue
                value = port.value or port.to
                if value:
                    self.set_ports({port.label: value}, ip=ip)

    def _set_meta(self, meta: Dict[str, str]) -> None:
        for k, v in meta.items():
            self.env[f"NOMAD_META_{_clean(k)}"] = str(v)
            self.env[f"NOMAD_META_{_clean(k).upper()}"] = str(v)

    def build(self) -> TaskEnv:
        return TaskEnv(dict(self.env), dict(self.node_attrs))
