"""Connect sidecar proxy: a minimal L4 forwarder standing in for the
reference's Envoy sidecar (nomad's connect integration injects a
"connect-proxy-<service>" task bootstrapped into Envoy; drivers/docker
+ envoybootstrap task-runner hook).

Run as a task:  ``python -m nomad_tpu.client.connect
--upstream web:9991 --upstream db:9992 [--inbound 8443:8080]``

* Each ``--upstream dest:port`` listens on 127.0.0.1:port and forwards
  every connection to the address in ``$NOMAD_CONNECT_TARGET_<DEST>``
  (resolved from the service catalog by the task runner at launch,
  exactly where the reference resolves upstreams into Envoy config).
  App tasks reach the upstream via ``$NOMAD_UPSTREAM_ADDR_<DEST>`` =
  ``127.0.0.1:<port>``, the same env contract the reference exposes.
* ``--inbound listen:target`` accepts mesh traffic and forwards to the
  local service port.
"""
from __future__ import annotations

import argparse
import os
import re
import socket
import sys
import threading


def env_key(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9]", "_", name).upper()


def _pump(src: socket.socket, dst: socket.socket) -> None:
    """One direction; on EOF propagate a half-close (SHUT_WR on dst)
    so the opposite direction keeps flowing — a client that shuts its
    write side still gets the full response."""
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        try:
            dst.shutdown(socket.SHUT_WR)
        except OSError:
            pass


def _handle(conn: socket.socket, out: socket.socket) -> None:
    a = threading.Thread(target=_pump, args=(conn, out), daemon=True)
    b = threading.Thread(target=_pump, args=(out, conn), daemon=True)
    a.start()
    b.start()
    a.join()
    b.join()
    for s in (conn, out):
        try:
            s.close()
        except OSError:
            pass


def _serve(listen_port: int, target: str) -> None:
    host, _, port = target.rpartition(":")
    addr = (host or "127.0.0.1", int(port))
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", listen_port))
    srv.listen(64)

    def accept_loop() -> None:
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            try:
                out = socket.create_connection(addr, timeout=10)
            except OSError:
                conn.close()
                continue
            threading.Thread(
                target=_handle, args=(conn, out), daemon=True
            ).start()

    threading.Thread(target=accept_loop, daemon=True).start()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="nomad-tpu-connect")
    p.add_argument(
        "--upstream", action="append", default=[],
        help="dest:local_bind_port",
    )
    p.add_argument(
        "--inbound", action="append", default=[],
        help="listen_port:local_service_port",
    )
    args = p.parse_args(argv)
    bound = 0
    for spec in args.upstream:
        dest, _, port = spec.rpartition(":")
        target = os.environ.get(f"NOMAD_CONNECT_TARGET_{env_key(dest)}")
        if not target:
            # fail the task (all-or-nothing): the restart loop relaunches
            # us and the task runner re-resolves from the catalog — the
            # eventual-consistency analog of Envoy's dynamic re-resolution
            print(
                f"upstream {dest!r} not resolvable yet; exiting for "
                "restart-retry",
                file=sys.stderr,
            )
            sys.exit(1)
        _serve(int(port), target)
        bound += 1
    for spec in args.inbound:
        listen, _, local = spec.partition(":")
        _serve(int(listen), f"127.0.0.1:{local}")
        bound += 1
    if not bound:
        print("nothing to proxy", file=sys.stderr)
        sys.exit(1)
    threading.Event().wait()  # park forever; the driver stops us


if __name__ == "__main__":
    main()
