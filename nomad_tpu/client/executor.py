"""Task executor: an isolated-process runtime for exec/raw_exec tasks
(reference drivers/shared/executor — the exec driver runs every task
under a *separate executor process* with libcontainer isolation
(executor_linux.go: chroot, namespaces, cgroups), speaking gRPC over
the go-plugin seam, and reattaches to it across client restarts).

This is the TPU-build equivalent over our framed wire protocol
(nomad_tpu/wire.py, the seam native/wire.cpp implements natively):

* **Executor process** — ``python -m nomad_tpu.client.executor`` binds
  a unix socket, prints the go-plugin-style handshake line
  ``1|1|unix|<socket>|wire`` and serves Launch/Wait/Signal/Stop/
  Destroy/Stats/ListTasks/Shutdown.  It owns the task subprocesses, so
  a driver (or whole client) restart cannot kill them.
* **Isolation** (applied in the child between fork and exec, the same
  window libcontainer uses):
    - private mount namespace (``unshare(CLONE_NEWNS)``),
    - ``chroot`` into the task sandbox, populated by hardlink (no data
      copied) from either a directory map (reference chroot_env) or
      the command's ldd closure (``link_command_env``),
    - cgroup cpu/memory limits — v1 and v2 hierarchies supported; the
      child enrolls *itself* before exec so no spawn escapes the
      limits,
    - own session (setsid) so stop/kill signals the whole tree.
  Each knob degrades gracefully (non-root, read-only cgroupfs): the
  task still runs, `launch` reports which isolations engaged.
* **Reattach** — the driver persists ``{socket, pid, task_id}`` per
  task (reference's ReattachConfig); `ExecutorClient.reconnect` dials
  the still-running executor after a restart and adopts the task.
"""
from __future__ import annotations

import json
import os
import shutil
import signal as _signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..wire import call, decode, encode, recv_frame, send_frame

HANDSHAKE = "1|1|unix|{path}|wire"

def _default_state_dir() -> str:
    """Reattach-record dir (reference: client state DB's driver handle
    blobs).  Never a predictable world-writable path: root uses /run,
    everyone else their home dir, both created 0700 and ownership-
    checked before any record is trusted."""
    if os.geteuid() == 0 and os.path.isdir("/run"):
        return "/run/nomad-tpu/executors"
    return os.path.join(
        os.path.expanduser("~"), ".nomad_tpu", "executors"
    )


STATE_DIR = os.environ.get(
    "NOMAD_TPU_EXECUTOR_STATE", _default_state_dir()
)


def _state_dir_trusted(path: str) -> bool:
    """Reject a records dir another user could have planted: it must
    belong to us and admit no group/other writes."""
    try:
        st = os.stat(path)
    except OSError:
        return False
    return st.st_uid == os.geteuid() and not (st.st_mode & 0o022)

CGROUP_ROOT = "/sys/fs/cgroup"
CGROUP_PARENT = "nomad_tpu"

# mount(2) flags for the bind-mounted sandbox
MS_RDONLY = 0x1
MS_REMOUNT = 0x20
MS_BIND = 0x1000
MS_REC = 0x4000
MS_PRIVATE = 0x40000

# system dirs bind-mounted read-only into a "bind"-populated sandbox
# (reference executor's default chroot env: /bin /etc /lib /lib64
# /sbin /usr — here as private bind mounts instead of file copies)
BIND_DIRS = ("/usr", "/etc", "/bin", "/sbin", "/lib", "/lib64")


def _libc():
    import ctypes

    return ctypes.CDLL(None, use_errno=True)


def _mount(source: bytes, target: bytes, fstype: bytes,
           flags: int) -> int:
    import ctypes

    libc = _libc()
    res = libc.mount(source, target, fstype, flags, None)
    return 0 if res == 0 else ctypes.get_errno()


# ---------------------------------------------------------------------------
# chroot population
# ---------------------------------------------------------------------------


def _link_tree(src: str, dest: str) -> None:
    """Mirror src into dest by hardlink (fallback: copy), preserving
    symlinks — the no-data-copied analog of the reference's chroot dir
    copy (client/allocdir/task_dir_linux.go)."""
    if os.path.islink(src):
        target = os.readlink(src)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if not os.path.lexists(dest):
            os.symlink(target, dest)
        return
    if os.path.isfile(src):
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if os.path.lexists(dest):
            return
        try:
            os.link(src, dest)
        except OSError:
            shutil.copy2(src, dest)
        return
    for root, dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        troot = dest if rel == "." else os.path.join(dest, rel)
        os.makedirs(troot, exist_ok=True)
        for d in list(dirs):
            sp = os.path.join(root, d)
            if os.path.islink(sp):
                dirs.remove(d)
                tp = os.path.join(troot, d)
                if not os.path.lexists(tp):
                    os.symlink(os.readlink(sp), tp)
        for f in files:
            sp, tp = os.path.join(root, f), os.path.join(troot, f)
            if os.path.lexists(tp):
                continue
            try:
                if os.path.islink(sp):
                    os.symlink(os.readlink(sp), tp)
                else:
                    os.link(sp, tp)
            except OSError:
                try:
                    shutil.copy2(sp, tp, follow_symlinks=False)
                except OSError:
                    pass


def prepare_bind_sandbox(dest: str) -> List[str]:
    """Create mount points mirroring the host's top-level layout
    (merged-usr symlinks preserved) and return the real dirs to
    bind-mount.  The mounts themselves happen in the child's private
    mount namespace (`_enter_bind_sandbox`), so nothing leaks to the
    host and teardown is automatic when the task's namespace dies —
    the reference gets the same from libcontainer's rootfs setup."""
    os.makedirs(dest, exist_ok=True)
    binds: List[str] = []
    for d in BIND_DIRS:
        if not os.path.exists(d):
            continue
        name = d.lstrip("/")
        target = os.path.join(dest, name)
        if os.path.islink(d):
            # e.g. /bin -> usr/bin: replicate the symlink; the /usr
            # bind covers its content
            if not os.path.lexists(target):
                os.symlink(os.readlink(d), target)
            continue
        os.makedirs(target, exist_ok=True)
        binds.append(d)
    for d in ("tmp", "dev", "proc", "alloc", "local", "secrets"):
        os.makedirs(os.path.join(dest, d), exist_ok=True)
    return binds


def _mount_task_dirs(
    chroot: str, mounts: List[Tuple[str, str]]
) -> None:
    """Bind the task-dir contract dirs (shared alloc, local, secrets)
    read-write into the sandbox so NOMAD_ALLOC_DIR/NOMAD_TASK_DIR/
    NOMAD_SECRETS_DIR resolve in-chroot (the reference bind-mounts the
    alloc dir into the chroot — alloc_dir_linux.go mountSharedDir).
    A failed bind ABORTS the launch: the parent already remapped the
    env vars to the in-chroot paths, so proceeding would silently
    write shared data into a private dir."""
    for host, rel in mounts:
        target = os.path.join(chroot, rel).encode()
        err = _mount(host.encode(), target, b"", MS_BIND)
        if err != 0:
            raise OSError(
                err, f"bind {host} -> /{rel} failed", host
            )


def _enter_bind_sandbox(
    chroot: str,
    binds: List[str],
    task_mounts: Optional[List[Tuple[str, str]]] = None,
) -> None:
    """Child-side (post-unshare(NEWNS), pre-exec): make mounts
    private, bind the system dirs read-only, mount /proc, chroot."""
    _mount(b"none", b"/", b"", MS_REC | MS_PRIVATE)
    for d in binds:
        target = os.path.join(chroot, d.lstrip("/")).encode()
        if _mount(d.encode(), target, b"", MS_BIND | MS_REC) == 0:
            # best-effort read-only remount of the bind
            _mount(b"none", target, b"",
                   MS_BIND | MS_REMOUNT | MS_RDONLY | MS_REC)
    # tasks need real device nodes (/dev/null, /dev/urandom, ...):
    # bind the host /dev read-write (reference libcontainer creates
    # the default device set in the rootfs)
    _mount(b"/dev", os.path.join(chroot, "dev").encode(), b"",
           MS_BIND | MS_REC)
    _mount(b"proc", os.path.join(chroot, "proc").encode(), b"proc", 0)
    if task_mounts:
        _mount_task_dirs(chroot, task_mounts)
    os.chroot(chroot)
    os.chdir("/")


def build_chroot(dest: str, env: Dict[str, str]) -> None:
    """Populate a chroot from a {source: dest-rel} map (reference
    executor's chroot_env / drivers.exec `chroot_env` config)."""
    os.makedirs(dest, exist_ok=True)
    for src, rel in env.items():
        if not os.path.lexists(src):
            continue
        target = os.path.join(dest, rel.lstrip("/"))
        _link_tree(src, target)
    for d in ("tmp", "dev", "proc"):
        os.makedirs(os.path.join(dest, d), exist_ok=True)


def link_command_env(dest: str, argv0: str) -> Dict[str, str]:
    """Minimal chroot env for one command: the binary plus its ldd
    closure (dynamic loader included).  Returns the map passed to
    build_chroot — a TPU-build refinement over copying whole /bin:/lib
    trees; callers wanting the reference's full default can pass their
    own map."""
    def chain(path: str) -> List[str]:
        # a path plus every hop of its symlink chain, so the chroot
        # reproduces e.g. /bin/sh -> dash -> (hardlinked file)
        out, p, hops = [], path, 0
        while hops < 16:
            out.append(p)
            if not os.path.islink(p):
                break
            p = os.path.normpath(
                os.path.join(os.path.dirname(p), os.readlink(p))
            )
            hops += 1
        return out

    env: Dict[str, str] = {}
    for p in chain(argv0):
        env[p] = p
    try:
        out = subprocess.run(
            ["ldd", argv0], capture_output=True, text=True, timeout=10
        ).stdout
    except (OSError, subprocess.TimeoutExpired):
        out = ""
    for line in out.splitlines():
        for tok in line.split():
            if tok.startswith("/") and os.path.exists(tok):
                for p in chain(tok):
                    env[p] = p
    return env


# ---------------------------------------------------------------------------
# cgroups (v1 + v2)
# ---------------------------------------------------------------------------


class CgroupSlice:
    """Per-task cgroup with cpu/memory limits.  The child writes its
    own pid into cgroup.procs pre-exec, so the whole task tree is
    enrolled from the first instruction (reference executor_linux.go
    configureCgroups via libcontainer)."""

    def __init__(self, task_id: str, cpu_shares: int = 0,
                 memory_mb: int = 0) -> None:
        self.task_id = task_id
        self.cpu_shares = int(cpu_shares)
        self.memory_mb = int(memory_mb)
        self.paths: List[str] = []
        self.v2 = os.path.exists(
            os.path.join(CGROUP_ROOT, "cgroup.controllers")
        )

    @staticmethod
    def _enable_v2_controllers() -> None:
        """cgroup v2 leaves only expose memory.max/cpu.weight when every
        ancestor delegates the controllers via cgroup.subtree_control."""
        for parent in (
            CGROUP_ROOT,
            os.path.join(CGROUP_ROOT, CGROUP_PARENT),
        ):
            ctl = os.path.join(parent, "cgroup.subtree_control")
            try:
                with open(ctl, "w") as f:
                    f.write("+memory +cpu")
            except OSError:
                pass

    def create(self) -> bool:
        try:
            if self.v2:
                os.makedirs(
                    os.path.join(CGROUP_ROOT, CGROUP_PARENT),
                    exist_ok=True,
                )
                self._enable_v2_controllers()
                path = os.path.join(
                    CGROUP_ROOT, CGROUP_PARENT, self.task_id
                )
                os.makedirs(path, exist_ok=True)
                if self.memory_mb:
                    self._write(
                        os.path.join(path, "memory.max"),
                        str(self.memory_mb * 1024 * 1024),
                    )
                if self.cpu_shares:
                    # v2 weight 1..10000; map shares/1024 -> 100
                    weight = max(
                        1, min(10000, self.cpu_shares * 100 // 1024)
                    )
                    self._write(
                        os.path.join(path, "cpu.weight"), str(weight)
                    )
                self.paths = [path]
                return True
            ok = False
            if self.memory_mb:
                path = os.path.join(
                    CGROUP_ROOT, "memory", CGROUP_PARENT, self.task_id
                )
                os.makedirs(path, exist_ok=True)
                self._write(
                    os.path.join(path, "memory.limit_in_bytes"),
                    str(self.memory_mb * 1024 * 1024),
                )
                self.paths.append(path)
                ok = True
            if self.cpu_shares:
                path = os.path.join(
                    CGROUP_ROOT, "cpu", CGROUP_PARENT, self.task_id
                )
                os.makedirs(path, exist_ok=True)
                self._write(
                    os.path.join(path, "cpu.shares"),
                    str(self.cpu_shares),
                )
                self.paths.append(path)
                ok = True
            return ok
        except OSError:
            self.destroy()
            return False

    @staticmethod
    def _write(path: str, value: str) -> None:
        with open(path, "w") as f:
            f.write(value)

    def enroll_self(self) -> None:
        """Called in the child pre-exec."""
        pid = str(os.getpid())
        for path in self.paths:
            try:
                self._write(os.path.join(path, "cgroup.procs"), pid)
            except OSError:
                pass

    def stats(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for path in self.paths:
            for fname, key, scale in (
                ("memory.current", "memory_rss_bytes", 1.0),
                ("memory.usage_in_bytes", "memory_rss_bytes", 1.0),
                ("cpuacct.usage", "cpu_total_ns", 1.0),
            ):
                fp = os.path.join(path, fname)
                if os.path.exists(fp):
                    try:
                        with open(fp) as f:
                            out[key] = float(f.read().strip())
                    except (OSError, ValueError):
                        pass
            stat = os.path.join(path, "cpu.stat")
            if self.v2 and os.path.exists(stat):
                try:
                    with open(stat) as f:
                        for line in f:
                            k, _, v = line.partition(" ")
                            if k == "usage_usec":
                                out["cpu_total_ns"] = float(v) * 1e3
                except (OSError, ValueError):
                    pass
        return out

    def destroy(self) -> None:
        for path in self.paths:
            procs = os.path.join(path, "cgroup.procs")
            try:
                with open(procs) as f:
                    for pid in f.read().split():
                        try:
                            os.kill(int(pid), _signal.SIGKILL)
                        except (ProcessLookupError, ValueError):
                            pass
            except OSError:
                pass
            for _ in range(10):
                try:
                    os.rmdir(path)
                    break
                except OSError:
                    time.sleep(0.05)
        self.paths = []


# ---------------------------------------------------------------------------
# the executor core
# ---------------------------------------------------------------------------


class _Task:
    def __init__(self, task_id: str, proc: subprocess.Popen,
                 cgroup: Optional[CgroupSlice], isolation: Dict) -> None:
        self.task_id = task_id
        self.proc = proc
        self.cgroup = cgroup
        self.isolation = isolation
        self.logmon = None
        self.exit: Optional[Dict] = None
        self.done = threading.Event()


class Executor:
    """In-process core; `serve` exposes it over the wire seam."""

    def __init__(self) -> None:
        self.tasks: Dict[str, _Task] = {}
        self._lock = threading.Lock()

    # -- launch --------------------------------------------------------

    def launch(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        task_id = spec["task_id"]
        argv = list(spec["argv"])
        cwd = spec.get("cwd") or None
        env = dict(spec.get("env") or {})
        isolation: Dict[str, Any] = {
            "chroot": False, "cgroups": False, "mount_ns": False,
        }

        can_unshare = os.geteuid() == 0 and hasattr(os, "unshare")
        chroot = spec.get("chroot") or ""
        binds: List[str] = []
        if chroot and os.geteuid() == 0:
            populate = spec.get("chroot_populate")
            if populate == "bind" or populate is None:
                if not can_unshare:
                    # without a private mount namespace the binds would
                    # land in the HOST mount table and outlive the
                    # task: refuse the sandbox rather than pollute
                    chroot = ""
                else:
                    binds = prepare_bind_sandbox(chroot)
            elif populate == "auto":
                build_chroot(chroot, link_command_env(chroot, argv[0]))
            elif isinstance(populate, dict) and populate:
                build_chroot(chroot, populate)
            isolation["chroot"] = True
        else:
            chroot = ""

        # task-dir contract: bind the shared alloc/local/secrets dirs
        # into the sandbox and remap the NOMAD_*_DIR env vars to the
        # in-chroot paths, so artifacts/templates/shared-data work
        # under the default chroot (reference alloc_dir_linux.go
        # mountSharedDir + taskenv's in-chroot paths)
        task_mounts: List[Tuple[str, str]] = []
        if (
            chroot
            and can_unshare
            and bool(spec.get("mount_ns", True))
        ):
            env_for_rel = {
                "alloc": "NOMAD_ALLOC_DIR",
                "local": "NOMAD_TASK_DIR",
                "secrets": "NOMAD_SECRETS_DIR",
            }
            for host, rel in spec.get("task_mounts") or []:
                rel = str(rel).strip("/")
                if not host or not os.path.isdir(host):
                    continue
                os.makedirs(os.path.join(chroot, rel), exist_ok=True)
                task_mounts.append((host, rel))
                var = env_for_rel.get(rel)
                if var and var in env:
                    env[var] = "/" + rel

        cgroup: Optional[CgroupSlice] = None
        if spec.get("use_cgroups", True) and (
            spec.get("cpu_shares") or spec.get("memory_mb")
        ):
            cgroup = CgroupSlice(
                task_id,
                cpu_shares=spec.get("cpu_shares", 0),
                memory_mb=spec.get("memory_mb", 0),
            )
            if cgroup.create():
                isolation["cgroups"] = True
            else:
                cgroup = None

        want_mnt_ns = bool(spec.get("mount_ns", True)) and can_unshare
        isolation["mount_ns"] = want_mnt_ns

        stdout = stderr = subprocess.DEVNULL
        use_logmon = bool(spec.get("logs_dir"))
        if use_logmon:
            # size-rotated logs, pumped by the executor itself — the
            # reference's executor pipes task output to logmon FIFOs
            # (drivers/shared/executor; client/logmon)
            stdout = stderr = subprocess.PIPE
        else:
            if spec.get("stdout_path"):
                os.makedirs(
                    os.path.dirname(spec["stdout_path"]), exist_ok=True
                )
                stdout = open(spec["stdout_path"], "ab")
            if spec.get("stderr_path"):
                os.makedirs(
                    os.path.dirname(spec["stderr_path"]), exist_ok=True
                )
                stderr = open(spec["stderr_path"], "ab")

        def pre_exec() -> None:
            # fork→exec window, the libcontainer init analog
            if cgroup is not None:
                cgroup.enroll_self()
            in_ns = False
            if want_mnt_ns:
                # fail closed for bind sandboxes: if we can't enter a
                # private namespace the binds would pollute the host,
                # so the raise below aborts the launch instead
                os.unshare(os.CLONE_NEWNS)
                in_ns = True
            if chroot:
                if binds:
                    if not in_ns:
                        raise OSError(
                            "bind sandbox requires a private mount "
                            "namespace"
                        )
                    _enter_bind_sandbox(chroot, binds, task_mounts)
                else:
                    if task_mounts and in_ns:
                        _mount(b"none", b"/", b"", MS_REC | MS_PRIVATE)
                        _mount_task_dirs(chroot, task_mounts)
                    os.chroot(chroot)
                    os.chdir("/")
            lim = spec.get("rlimit_nofile")
            if lim:
                import resource

                resource.setrlimit(
                    resource.RLIMIT_NOFILE, (int(lim), int(lim))
                )

        if cwd and not chroot:
            os.makedirs(cwd, exist_ok=True)
        try:
            proc = subprocess.Popen(
                argv,
                cwd=None if chroot else cwd,
                env=env,
                stdout=stdout,
                stderr=stderr,
                start_new_session=True,
                preexec_fn=pre_exec,
            )
        except OSError as exc:
            if cgroup is not None:
                cgroup.destroy()
            raise RuntimeError(f"launch failed: {exc}") from exc
        finally:
            for fh in (stdout, stderr):
                if fh not in (subprocess.DEVNULL, subprocess.PIPE):
                    fh.close()

        logmon = None
        if use_logmon:
            from .logmon import LogMon

            logmon = LogMon(
                spec["logs_dir"],
                spec.get("log_name") or task_id,
                max_files=int(spec.get("log_max_files", 10)),
                max_file_size_mb=int(
                    spec.get("log_max_file_size_mb", 10)
                ),
            )
            logmon.pump(proc.stdout, "stdout")
            logmon.pump(proc.stderr, "stderr")

        task = _Task(task_id, proc, cgroup, isolation)
        task.logmon = logmon
        with self._lock:
            self.tasks[task_id] = task

        def waiter() -> None:
            code = proc.wait()
            if task.logmon is not None:
                task.logmon.wait(2.0)
                task.logmon.close()
            if code < 0:
                task.exit = {"exit_code": 0, "signal": -code}
            else:
                task.exit = {"exit_code": code, "signal": 0}
            if task.cgroup is not None:
                # OOM kill shows up as SIGKILL + memory events
                task.exit["oom_killed"] = self._was_oom(task)
            # persist the exit beside the reattach record BEFORE
            # signalling completion: if the executor self-reaps while
            # the client is down, recovery still reports the real
            # status instead of 'lost'
            save_exit_record(task_id, task.exit)
            task.done.set()

        threading.Thread(target=waiter, daemon=True).start()
        return {"pid": proc.pid, "isolation": isolation}

    @staticmethod
    def _was_oom(task: _Task) -> bool:
        for path in task.cgroup.paths if task.cgroup else ():
            for fname in ("memory.events", "memory.oom_control"):
                fp = os.path.join(path, fname)
                try:
                    with open(fp) as f:
                        for line in f:
                            k, _, v = line.strip().partition(" ")
                            if k in ("oom_kill", "oom_kill_disable"):
                                if k == "oom_kill" and v and int(v) > 0:
                                    return True
                except (OSError, ValueError):
                    continue
        return False

    # -- lifecycle -----------------------------------------------------

    def wait(self, task_id: str, timeout: Optional[float]) -> Optional[Dict]:
        task = self.tasks.get(task_id)
        if task is None:
            return {"exit_code": 0, "err": "unknown task"}
        if not task.done.wait(timeout):
            return None
        return task.exit

    def signal(self, task_id: str, sig: str) -> None:
        task = self.tasks.get(task_id)
        if task is None or task.done.is_set():
            return
        name = sig if sig.startswith("SIG") else f"SIG{sig}"
        signum = _signal.Signals[name]
        try:
            os.killpg(os.getpgid(task.proc.pid), signum)
        except ProcessLookupError:
            pass

    def stop(self, task_id: str, timeout: float, sig: str) -> None:
        task = self.tasks.get(task_id)
        if task is None:
            return
        self.signal(task_id, sig)
        if not task.done.wait(timeout):
            try:
                os.killpg(os.getpgid(task.proc.pid), _signal.SIGKILL)
            except ProcessLookupError:
                pass
            task.done.wait(2.0)

    def destroy(self, task_id: str, force: bool) -> None:
        task = self.tasks.get(task_id)
        if task is None:
            return
        if not task.done.is_set():
            if not force:
                raise RuntimeError("task is still running")
            self.stop(task_id, 0.5, "SIGKILL")
        if task.cgroup is not None:
            task.cgroup.destroy()
        with self._lock:
            self.tasks.pop(task_id, None)

    def stats(self, task_id: str) -> Dict[str, float]:
        task = self.tasks.get(task_id)
        if task is None:
            return {}
        if task.cgroup is not None:
            out = task.cgroup.stats()
            if out:
                return out
        # /proc fallback
        try:
            with open(f"/proc/{task.proc.pid}/statm") as f:
                rss_pages = int(f.read().split()[1])
            return {
                "memory_rss_bytes": float(
                    rss_pages * os.sysconf("SC_PAGE_SIZE")
                )
            }
        except (OSError, IndexError, ValueError):
            return {}

    def list_tasks(self) -> List[Dict[str, Any]]:
        return [
            {
                "task_id": t.task_id,
                "pid": t.proc.pid,
                "running": not t.done.is_set(),
                "isolation": t.isolation,
            }
            for t in self.tasks.values()
        ]


# ---------------------------------------------------------------------------
# wire serving (plugin side)
# ---------------------------------------------------------------------------


def serve(socket_path: str = "") -> None:
    socket_path = socket_path or os.path.join(
        tempfile.mkdtemp(prefix="nomad-executor-"), "executor.sock"
    )
    ex = Executor()
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(socket_path)
    srv.listen(8)
    print(HANDSHAKE.format(path=socket_path), flush=True)
    shutdown = threading.Event()

    def dispatch(method: str, body: Dict) -> Any:
        if method == "Launch":
            return ex.launch(body)
        if method == "Wait":
            return ex.wait(body["task_id"], body.get("timeout"))
        if method == "Signal":
            ex.signal(body["task_id"], body.get("signal", "SIGTERM"))
            return {}
        if method == "Stop":
            ex.stop(
                body["task_id"],
                body.get("timeout", 5.0),
                body.get("signal", "SIGTERM"),
            )
            return {}
        if method == "Destroy":
            ex.destroy(body["task_id"], body.get("force", False))
            return {}
        if method == "Stats":
            return ex.stats(body["task_id"])
        if method == "ListTasks":
            return ex.list_tasks()
        if method == "Shutdown":
            shutdown.set()
            return {}
        raise ValueError(f"unknown method {method!r}")

    conns: set = set()

    def handle(conn: socket.socket) -> None:
        conns.add(conn)
        try:
            while True:
                frame = recv_frame(conn)
                if frame is None:
                    return
                method, body = decode(frame)
                try:
                    result = dispatch(method, body)
                except Exception as exc:  # noqa: BLE001
                    result = {"error": f"{type(exc).__name__}: {exc}"}
                send_frame(conn, encode(result))
        finally:
            conns.discard(conn)

    def acceptor() -> None:
        while not shutdown.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(
                target=handle, args=(conn,), daemon=True
            ).start()

    def idle_reaper() -> None:
        # self-exit when no driver is attached AND no task is running:
        # done tasks with a vanished client must not leak executor
        # processes, while a live task keeps the executor up for
        # reattach (reference: go-plugin kills executors whose tasks
        # died; reattach keeps them only while the task lives)
        idle_since: Optional[float] = None
        while not shutdown.is_set():
            time.sleep(2.0)
            busy = bool(conns) or any(
                not t.done.is_set() for t in list(ex.tasks.values())
            )
            if busy:
                idle_since = None
            elif idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since > 15.0:
                shutdown.set()

    threading.Thread(target=acceptor, daemon=True).start()
    threading.Thread(target=idle_reaper, daemon=True).start()
    while not shutdown.is_set():
        shutdown.wait(0.2)
    srv.close()


# ---------------------------------------------------------------------------
# host side
# ---------------------------------------------------------------------------


class ExecutorClient:
    """Driver-side proxy to one executor process (reference
    drivers/shared/executor grpc client + go-plugin ReattachConfig)."""

    def __init__(self, sock: socket.socket, socket_path: str,
                 proc: Optional[subprocess.Popen] = None) -> None:
        self.sock = sock
        self.socket_path = socket_path
        self.proc = proc
        self._lock = threading.Lock()

    @classmethod
    def spawn(cls) -> "ExecutorClient":
        # the supervisor itself never touches jax: keep it off the
        # exclusive accelerator session (a leftover executor holding
        # the tunneled chip is how round 3 lost its benchmark)
        from ..device_lock import scrub_accelerator_env

        proc = subprocess.Popen(
            [sys.executable, "-m", "nomad_tpu.client.executor"],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=scrub_accelerator_env(),
        )
        line = (proc.stdout.readline() or "").strip()
        parts = line.split("|")
        if len(parts) != 5 or parts[2] != "unix":
            proc.kill()
            raise RuntimeError(f"bad executor handshake: {line!r}")
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(60.0)
        sock.connect(parts[3])
        return cls(sock, parts[3], proc)

    @classmethod
    def reconnect(cls, socket_path: str) -> "ExecutorClient":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(60.0)
        sock.connect(socket_path)
        return cls(sock, socket_path, None)

    def _call(self, method: str, body: Any,
              timeout: float = 30.0) -> Any:
        with self._lock:
            self.sock.settimeout(timeout + 10.0)
            resp = call(self.sock, method, body)
        if isinstance(resp, dict) and resp.get("error"):
            raise RuntimeError(resp["error"])
        return resp

    def launch(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        return self._call("Launch", spec)

    def wait(self, task_id: str,
             timeout: Optional[float] = None) -> Optional[Dict]:
        # bounded slices: single-in-flight wire (see ExternalDriver)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            slice_ = 1.0
            if deadline is not None:
                slice_ = min(1.0, max(0.0, deadline - time.monotonic()))
            raw = self._call(
                "Wait", {"task_id": task_id, "timeout": slice_},
                timeout=slice_ + 5.0,
            )
            if raw is not None:
                return raw
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def signal(self, task_id: str, sig: str = "SIGTERM") -> None:
        self._call("Signal", {"task_id": task_id, "signal": sig})

    def stop(self, task_id: str, timeout: float = 5.0,
             sig: str = "SIGTERM") -> None:
        self._call(
            "Stop",
            {"task_id": task_id, "timeout": timeout, "signal": sig},
            timeout=timeout + 10.0,
        )

    def destroy(self, task_id: str, force: bool = False) -> None:
        self._call("Destroy", {"task_id": task_id, "force": force})

    def stats(self, task_id: str) -> Dict[str, float]:
        return self._call("Stats", {"task_id": task_id}) or {}

    def list_tasks(self) -> List[Dict[str, Any]]:
        return self._call("ListTasks", {}) or []

    def shutdown(self) -> None:
        try:
            self._call("Shutdown", {}, timeout=5.0)
        except (RuntimeError, OSError):
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self.proc is not None:
            try:
                self.proc.wait(5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()


# -- reattach records -------------------------------------------------------


def save_reattach(task_id: str, socket_path: str, pid: int) -> None:
    os.makedirs(STATE_DIR, mode=0o700, exist_ok=True)
    if not _state_dir_trusted(STATE_DIR):
        return
    with open(os.path.join(STATE_DIR, f"{task_id}.json"), "w") as f:
        json.dump({"socket": socket_path, "pid": pid}, f)


def load_reattach(task_id: str) -> Optional[Dict[str, Any]]:
    if not _state_dir_trusted(STATE_DIR):
        return None
    try:
        with open(os.path.join(STATE_DIR, f"{task_id}.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def drop_reattach(task_id: str) -> None:
    try:
        os.unlink(os.path.join(STATE_DIR, f"{task_id}.json"))
    except OSError:
        pass
    drop_exit_record(task_id)


def save_exit_record(task_id: str, exit: Dict[str, Any]) -> None:
    """Persist a finished task's exit status beside the reattach
    record.  The executor self-reaps 15s after its last task finishes;
    a client restart slower than that must still report the REAL exit
    (a completed batch task re-run as 'lost' runs twice)."""
    os.makedirs(STATE_DIR, mode=0o700, exist_ok=True)
    if not _state_dir_trusted(STATE_DIR):
        return
    path = os.path.join(STATE_DIR, f"{task_id}.exit.json")
    tmp = f"{path}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(exit, f)
        os.replace(tmp, path)
    except OSError:
        pass


def load_exit_record(task_id: str) -> Optional[Dict[str, Any]]:
    if not _state_dir_trusted(STATE_DIR):
        return None
    try:
        with open(
            os.path.join(STATE_DIR, f"{task_id}.exit.json")
        ) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def drop_exit_record(task_id: str) -> None:
    try:
        os.unlink(os.path.join(STATE_DIR, f"{task_id}.exit.json"))
    except OSError:
        pass


if __name__ == "__main__":
    serve(sys.argv[1] if len(sys.argv) > 1 else "")
