"""Client-side allocation garbage collection (reference client/gc.go).

Terminal alloc runners are tracked in an LRU-by-termination-time heap;
GC destroys their alloc dirs when any of the reference's triggers fire
(gc.go:AllocCounter + MakeRoomFor):

* more than ``max_allocs`` total allocs exist on the client,
* available disk in the alloc mount drops below ``disk_usable_mb``
  or usage rises above ``disk_usage_threshold`` percent,
* an explicit ``collect_all`` (the ``/v1/client/gc`` surface).

New placements call ``make_room_for`` first, mirroring how the
reference GCs before building the next alloc dir.
"""
from __future__ import annotations

import heapq
import itertools
import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_MAX_ALLOCS = 50
DEFAULT_DISK_USAGE_THRESHOLD = 80.0  # percent
DEFAULT_MIN_USABLE_MB = 100


class AllocGarbageCollector:
    def __init__(
        self,
        alloc_base_dir: str = "",
        max_allocs: int = DEFAULT_MAX_ALLOCS,
        disk_usage_threshold: float = DEFAULT_DISK_USAGE_THRESHOLD,
        min_usable_mb: int = DEFAULT_MIN_USABLE_MB,
        destroy_fn: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.alloc_base_dir = alloc_base_dir
        self.max_allocs = max_allocs
        self.disk_usage_threshold = disk_usage_threshold
        self.min_usable_mb = min_usable_mb
        self.destroy_fn = destroy_fn
        self._lock = threading.Lock()
        self._heap: List[Tuple[float, int, str]] = []
        self._entries: Dict[str, float] = {}
        self._live = 0
        self._counter = itertools.count()
        # allocs pinned against collection (migration predecessors
        # whose sticky data hasn't been pulled yet); refcounted
        self._protected: Dict[str, int] = {}

    # ------------------------------------------------------------------

    def set_live_count(self, n: int) -> None:
        with self._lock:
            self._live = n

    def mark_terminal(self, alloc_id: str) -> None:
        """(reference gc.go MarkForCollection)"""
        with self._lock:
            if alloc_id in self._entries:
                return
            ts = time.time()
            self._entries[alloc_id] = ts
            heapq.heappush(
                self._heap, (ts, next(self._counter), alloc_id)
            )

    def remove(self, alloc_id: str) -> None:
        with self._lock:
            self._entries.pop(alloc_id, None)

    def protect(self, alloc_id: str) -> None:
        """Pin an alloc against GC until unprotect (e.g. while a
        successor still needs its sticky ephemeral-disk data)."""
        with self._lock:
            self._protected[alloc_id] = (
                self._protected.get(alloc_id, 0) + 1
            )

    def unprotect(self, alloc_id: str) -> None:
        with self._lock:
            n = self._protected.get(alloc_id, 0) - 1
            if n <= 0:
                self._protected.pop(alloc_id, None)
            else:
                self._protected[alloc_id] = n

    def num_marked(self) -> int:
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------------------

    def _pop_oldest(self, exclude=None) -> Optional[str]:
        skipped: List[Tuple[float, int, str]] = []
        found: Optional[str] = None
        with self._lock:
            while self._heap:
                entry = heapq.heappop(self._heap)
                alloc_id = entry[2]
                if alloc_id not in self._entries:
                    continue
                if (exclude and alloc_id in exclude) or (
                    alloc_id in self._protected
                ):
                    skipped.append(entry)
                    continue
                del self._entries[alloc_id]
                found = alloc_id
                break
            for entry in skipped:
                heapq.heappush(self._heap, entry)
        return found

    def _destroy(self, alloc_id: str) -> None:
        if self.destroy_fn is not None:
            self.destroy_fn(alloc_id)
        elif self.alloc_base_dir:
            shutil.rmtree(
                os.path.join(self.alloc_base_dir, alloc_id),
                ignore_errors=True,
            )

    def _disk_stats(self) -> Optional[Tuple[float, float]]:
        """(used_percent, usable_mb) of the alloc mount, or None."""
        if not self.alloc_base_dir or not os.path.isdir(
            self.alloc_base_dir
        ):
            return None
        try:
            st = os.statvfs(self.alloc_base_dir)
        except OSError:
            return None
        total = st.f_blocks * st.f_frsize
        avail = st.f_bavail * st.f_frsize
        if total <= 0:
            return None
        used_pct = 100.0 * (total - avail) / total
        return used_pct, avail / (1024 * 1024)

    # ------------------------------------------------------------------

    def collect(self, alloc_id: str) -> bool:
        """GC one specific terminal alloc (reference gc.go Collect)."""
        with self._lock:
            present = alloc_id in self._entries
            if present:
                del self._entries[alloc_id]
        if present:
            self._destroy(alloc_id)
        return present

    def collect_all(self) -> int:
        """(reference gc.go CollectAll, the /v1/client/gc path)"""
        n = 0
        while True:
            alloc_id = self._pop_oldest()
            if alloc_id is None:
                return n
            self._destroy(alloc_id)
            n += 1

    def make_room_for(self, new_allocs: int = 1, exclude=None) -> int:
        """GC until the client can take `new_allocs` more
        (reference gc.go MakeRoomFor).  `exclude` protects allocs that
        must survive (e.g. a migration predecessor)."""
        n = 0
        while True:
            with self._lock:
                total = self._live + len(self._entries)
            if total + new_allocs <= self.max_allocs:
                break
            alloc_id = self._pop_oldest(exclude)
            if alloc_id is None:
                break
            self._destroy(alloc_id)
            n += 1
        n += self._gc_for_disk(exclude)
        return n

    def _gc_for_disk(self, exclude=None) -> int:
        n = 0
        while True:
            stats = self._disk_stats()
            if stats is None:
                return n
            used_pct, usable_mb = stats
            if (
                used_pct < self.disk_usage_threshold
                and usable_mb > self.min_usable_mb
            ):
                return n
            alloc_id = self._pop_oldest(exclude)
            if alloc_id is None:
                return n
            self._destroy(alloc_id)
            n += 1

    def periodic(self) -> int:
        """One periodic pass (reference gc.go run loop body)."""
        return self.make_room_for(0)
