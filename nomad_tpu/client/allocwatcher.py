"""Previous-allocation watcher + ephemeral disk migration
(reference client/allocwatcher/alloc_watcher.go).

An allocation that replaces another (``alloc.previous_allocation``, set
by the reconciler for reschedules and drains) must wait for its
predecessor to terminate before starting, and — when the task group's
``ephemeral_disk`` sets ``sticky``/``migrate`` — inherit the
predecessor's shared data dir and task local dirs.

Two cases, as in the reference:

* **local** (``localPrevAlloc``): the previous alloc ran on this node;
  wait on the local runner, then move dirs with ``AllocDir.move_from``.
* **remote** (``remotePrevAlloc``): it ran elsewhere; poll the servers
  until the alloc is terminal.  Data migration then pulls a snapshot
  through the server's fs proxy — modeled here as a pluggable
  ``fetch_snapshot`` callable so transports can evolve independently.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from .allocdir import AllocDir, find_alloc_dir


class NoopPrevAlloc:
    """Placeholder when there is no previous alloc to wait for."""

    def wait(self, timeout: Optional[float] = None) -> bool:
        return True

    def migrate(self, dest: AllocDir) -> bool:
        return False


class PrevAllocWatcher:
    def __init__(
        self,
        prev_alloc_id: str,
        sticky: bool = False,
        migrate: bool = False,
        # local case
        prev_runner=None,
        alloc_base_dir: str = "",
        # remote case
        poll_terminal: Optional[Callable[[str], bool]] = None,
        fetch_snapshot: Optional[Callable[[str, AllocDir], bool]] = None,
        poll_interval: float = 0.1,
    ) -> None:
        self.prev_alloc_id = prev_alloc_id
        self.sticky = sticky
        self.migrate_data = migrate
        self.prev_runner = prev_runner
        self.alloc_base_dir = alloc_base_dir
        self.poll_terminal = poll_terminal
        self.fetch_snapshot = fetch_snapshot
        self.poll_interval = poll_interval
        self._waited = threading.Event()

    @property
    def is_local(self) -> bool:
        return self.prev_runner is not None

    # ------------------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the previous alloc is terminal
        (reference alloc_watcher.go Wait)."""
        if self.prev_runner is not None:
            # a runner that failed before starting tasks (e.g. CSI
            # mount) is terminal without its task waits ever firing
            term = getattr(self.prev_runner, "is_terminal", None)
            if callable(term) and term():
                self._waited.set()
                return True
            ok = self.prev_runner.wait(timeout)
            if ok:
                self._waited.set()
            return ok
        if self.poll_terminal is None:
            self._waited.set()
            return True
        deadline = None
        if timeout is not None:
            import time as _time

            deadline = _time.monotonic() + timeout
        while True:
            if self.poll_terminal(self.prev_alloc_id):
                self._waited.set()
                return True
            import time as _time

            if deadline is not None and _time.monotonic() >= deadline:
                return False
            _time.sleep(self.poll_interval)

    # ------------------------------------------------------------------

    def migrate(self, dest: AllocDir) -> bool:
        """Move/fetch the sticky data into `dest`
        (reference alloc_watcher.go Migrate).  Returns True if any data
        was migrated."""
        if not (self.sticky or self.migrate_data):
            return False
        if not self._waited.is_set():
            # refuse to copy from a still-running alloc
            return False
        # local data first: the runner's own dir, else whatever is
        # still on disk under the alloc base dir
        prev_dir = None
        if self.prev_runner is not None:
            prev_dir = getattr(self.prev_runner, "alloc_dir_obj", None)
        if prev_dir is None and self.alloc_base_dir:
            prev_dir = find_alloc_dir(
                self.alloc_base_dir, self.prev_alloc_id
            )
        if prev_dir is not None:
            dest.move_from(prev_dir)
            return True
        # nothing local: remote pull (reference remotePrevAlloc
        # Migrate streaming the snapshot through the servers)
        if self.fetch_snapshot is not None and self.migrate_data:
            return self.fetch_snapshot(self.prev_alloc_id, dest)
        return False


def watcher_for_alloc(
    alloc,
    local_runners,
    alloc_base_dir: str = "",
    poll_terminal: Optional[Callable[[str], bool]] = None,
    fetch_snapshot: Optional[Callable[[str, AllocDir], bool]] = None,
):
    """Build the right watcher for an alloc
    (reference allocwatcher.NewAllocWatcher factory)."""
    prev_id = alloc.previous_allocation
    if not prev_id:
        return NoopPrevAlloc()
    tg = (
        alloc.job.lookup_task_group(alloc.task_group)
        if alloc.job is not None
        else None
    )
    disk = tg.ephemeral_disk if tg is not None else None
    sticky = bool(disk and disk.sticky)
    migrate = bool(disk and disk.migrate)
    prev_runner = (
        local_runners.get(prev_id)
        if local_runners is not None
        else None
    )
    if prev_runner is not None:
        return PrevAllocWatcher(
            prev_id,
            sticky=sticky,
            migrate=migrate,
            prev_runner=prev_runner,
            alloc_base_dir=alloc_base_dir,
        )
    return PrevAllocWatcher(
        prev_id,
        sticky=sticky,
        migrate=migrate,
        alloc_base_dir=alloc_base_dir if sticky or migrate else "",
        poll_terminal=poll_terminal,
        fetch_snapshot=fetch_snapshot,
    )
