"""Artifact fetching (reference
client/allocrunner/taskrunner/getter/getter.go, which wraps go-getter).

Each task artifact is ``{"source": ..., "destination": ..., "mode":
"any|file|dir", "options": {"checksum": "sha256:<hex>"}}``.  Supported
schemes: ``file://`` and bare local paths (copy), ``http(s)://`` via
urllib.  Downloads land under the task's local dir unless `destination`
is absolute-ish; checksum mismatches fail the fetch, which the task
runner surfaces as a failed-setup task event exactly like the
reference's artifact hook.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import urllib.parse
import urllib.request
from typing import Dict, List


class ArtifactError(Exception):
    pass


def contained_path(root: str, rel: str) -> str:
    """Resolve `rel` under `root`, refusing escapes — the shared
    sandbox check for artifact destinations, dispatch payload files and
    the alloc fs API.  Raises ValueError on escape."""
    real_root = os.path.realpath(root)
    p = os.path.realpath(os.path.join(root, rel.lstrip("/")))
    if p != real_root and not p.startswith(real_root + os.sep):
        raise ValueError(f"path {rel!r} escapes {root!r}")
    return p


def _verify_checksum(path: str, spec: str) -> None:
    """`spec` is "<algo>:<hexdigest>" (go-getter checksum option)."""
    try:
        algo, want = spec.split(":", 1)
        h = hashlib.new(algo)
    except ValueError as exc:
        raise ArtifactError(f"bad checksum spec {spec!r}") from exc
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(65536), b""):
            h.update(chunk)
    if h.hexdigest() != want.lower():
        raise ArtifactError(
            f"checksum mismatch for {path}: got {h.hexdigest()}, "
            f"want {want}"
        )


def fetch_artifact(artifact: Dict, task_local_dir: str) -> str:
    """Fetch one artifact into the task dir; returns the landed path."""
    source = artifact.get("source", "")
    if not source:
        raise ArtifactError("artifact has no source")
    dest_rel = artifact.get("destination", "") or "local"
    # destinations are always sandboxed under the task local dir
    # (reference getter.go getDestination rejects escapes)
    try:
        dest_dir = contained_path(task_local_dir, dest_rel)
    except ValueError:
        raise ArtifactError(
            f"artifact destination {dest_rel!r} escapes the task dir"
        )
    os.makedirs(dest_dir, exist_ok=True)

    parsed = urllib.parse.urlparse(source)
    checksum = (artifact.get("options") or {}).get("checksum", "")

    if parsed.scheme in ("http", "https"):
        name = os.path.basename(parsed.path) or "artifact"
        out = os.path.join(dest_dir, name)
        try:
            with urllib.request.urlopen(source, timeout=30) as resp:
                with open(out, "wb") as f:
                    shutil.copyfileobj(resp, f)
        except Exception as exc:  # noqa: BLE001
            raise ArtifactError(
                f"failed to download {source}: {exc}"
            ) from exc
    elif parsed.scheme in ("", "file"):
        src = parsed.path if parsed.scheme == "file" else source
        if not os.path.exists(src):
            raise ArtifactError(f"artifact source {src} not found")
        if os.path.isdir(src):
            out = os.path.join(dest_dir, os.path.basename(src.rstrip("/")))
            shutil.copytree(src, out, dirs_exist_ok=True)
        else:
            out = os.path.join(dest_dir, os.path.basename(src))
            shutil.copy2(src, out)
    else:
        raise ArtifactError(
            f"unsupported artifact scheme {parsed.scheme!r}"
        )

    if checksum:
        if not os.path.isfile(out):
            raise ArtifactError(
                f"checksum requested but {out} is not a regular file "
                "(directories cannot be checksummed)"
            )
        _verify_checksum(out, checksum)
    return out


def fetch_all(artifacts: List[Dict], task_local_dir: str) -> List[str]:
    return [fetch_artifact(a, task_local_dir) for a in artifacts]
