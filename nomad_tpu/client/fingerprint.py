"""Host fingerprinting (reference client/fingerprint/): detect resources,
attributes and devices and fold them into the Node.

TPU-native: accelerators are fingerprinted through JAX (`jax.devices()`)
into the node's device inventory — the TPU equivalent of the reference's
nvml-based GPU fingerprinter (devices/gpu/nvidia/device.go:88)."""
from __future__ import annotations

import os
import platform
import socket
from typing import Dict, List, Optional

from ..structs import Node, NodeDeviceResource, NodeResources


def fingerprint_arch(node: Node) -> None:
    node.attributes["cpu.arch"] = platform.machine()
    node.attributes["kernel.name"] = platform.system().lower()
    node.attributes["kernel.version"] = platform.release()
    node.attributes["os.name"] = platform.system().lower()


def fingerprint_cpu(node: Node) -> None:
    ncores = os.cpu_count() or 1
    node.attributes["cpu.numcores"] = str(ncores)
    mhz = 2400  # conservative default when frequency is unavailable
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = int(float(line.split(":")[1]))
                    break
    except OSError:
        pass
    node.attributes["cpu.frequency"] = str(mhz)
    total = ncores * mhz
    node.attributes["cpu.totalcompute"] = str(total)
    if node.node_resources.cpu <= 0:
        node.node_resources.cpu = total


def fingerprint_memory(node: Node) -> None:
    total_mb = 1024
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except OSError:
        pass
    node.attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
    if node.node_resources.memory_mb <= 0:
        node.node_resources.memory_mb = total_mb


def fingerprint_storage(node: Node, path: str = "/") -> None:
    try:
        stat = os.statvfs(path)
        free_mb = stat.f_bavail * stat.f_frsize // (1024 * 1024)
    except OSError:
        free_mb = 10 * 1024
    node.attributes["unique.storage.volume"] = path
    node.attributes["unique.storage.bytesfree"] = str(
        free_mb * 1024 * 1024
    )
    if node.node_resources.disk_mb <= 0:
        node.node_resources.disk_mb = free_mb


def fingerprint_host(node: Node) -> None:
    node.attributes["unique.hostname"] = socket.gethostname()
    if not node.name:
        node.name = socket.gethostname()


def bounded_jax_devices(timeout_s: Optional[float] = None):
    """`jax.devices()` with a deadline.  On shared/tunneled
    accelerators the enumeration can block indefinitely while another
    process holds the chip; callers (node fingerprint, TPU device
    plugin) must not wedge the client agent on it.  Returns None on
    timeout/failure — a node that registers CPU-only stays CPU-only
    until restart, which is the accepted trade for registering at
    all."""
    import threading

    from ..device_lock import align_jax_platforms

    align_jax_platforms()
    if timeout_s is None:
        timeout_s = float(
            os.environ.get("NOMAD_TPU_FINGERPRINT_TIMEOUT_S", "20")
        )
    box: Dict[str, List] = {}

    def enumerate_devices() -> None:
        try:
            # exclusive accelerator lock before backend init: a
            # second jax process wedges a tunneled single-chip
            # session.  The wait is bounded by THIS enumeration's
            # deadline — the orphaned thread must not acquire the
            # process-lifetime lock long after the caller gave up
            # (the node registered CPU-only; holding the chip then
            # starves every other process of it)
            from ..device_lock import ensure_device_lock

            if not ensure_device_lock(
                "client fingerprint", wait_s=timeout_s
            ):
                return
            import jax

            box["devices"] = jax.devices()
        except Exception:  # noqa: BLE001
            pass

    t = threading.Thread(target=enumerate_devices, daemon=True)
    t.start()
    t.join(timeout_s)
    return box.get("devices")


def fingerprint_tpu(node: Node) -> None:
    """Detect attached accelerators via JAX; import is deferred and
    failures are non-fatal so CPU-only clients fingerprint cleanly."""
    devices = bounded_jax_devices()
    if devices is None:
        return
    by_kind: Dict[str, List] = {}
    for d in devices:
        if d.platform in ("cpu",):
            continue
        by_kind.setdefault(d.device_kind, []).append(d)
    for kind, devs in by_kind.items():
        node.node_resources.devices.append(
            NodeDeviceResource(
                vendor="google",
                type="tpu",
                name=kind.replace(" ", "-").lower(),
                instance_ids=[str(d.id) for d in devs],
                attributes={
                    "platform": devs[0].platform,
                    "count": str(len(devs)),
                },
            )
        )
        node.attributes["tpu.count"] = str(len(devs))
        node.attributes["tpu.kind"] = kind


def fingerprint_drivers(node: Node, drivers: Dict[str, object]) -> None:
    for name, driver in drivers.items():
        for key, value in driver.fingerprint().items():
            node.attributes[key] = value
        node.drivers[name] = True


ALL_FINGERPRINTERS = [
    fingerprint_arch,
    fingerprint_cpu,
    fingerprint_memory,
    fingerprint_storage,
    fingerprint_host,
    fingerprint_tpu,
]


def run_fingerprinters(node: Node, include_tpu: bool = True) -> Node:
    for fp in ALL_FINGERPRINTERS:
        if fp is fingerprint_tpu and not include_tpu:
            continue
        fp(node)
    return node
