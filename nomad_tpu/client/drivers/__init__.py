"""Task drivers.

The reference runs drivers as separate go-plugin gRPC processes
(plugins/drivers/driver.go:40 DriverPlugin: Fingerprint, StartTask,
WaitTask, StopTask, DestroyTask, ...).  Here drivers implement the same
lifecycle surface in-process behind a registry; the executor boundary
(subprocess isolation for exec/raw_exec) is the process seam instead.
"""
from typing import Dict, Type

from .base import DriverHandle, DriverPlugin, TaskExitResult
from .mock import MockDriver
from .exec import ExecDriver, RawExecDriver
from .java import JavaDriver
from .qemu import QemuDriver
from .docker import DockerDriver

BUILTIN_DRIVERS: Dict[str, Type[DriverPlugin]] = {
    "mock_driver": MockDriver,
    "exec": ExecDriver,
    "raw_exec": RawExecDriver,
    "java": JavaDriver,
    "qemu": QemuDriver,
    "docker": DockerDriver,
}


def new_driver(name: str) -> DriverPlugin:
    cls = BUILTIN_DRIVERS.get(name)
    if cls is None:
        raise KeyError(f"unknown driver {name!r}")
    return cls()


__all__ = [
    "BUILTIN_DRIVERS",
    "new_driver",
    "DriverPlugin",
    "DriverHandle",
    "TaskExitResult",
    "MockDriver",
    "ExecDriver",
    "RawExecDriver",
    "JavaDriver",
    "QemuDriver",
    "DockerDriver",
]
