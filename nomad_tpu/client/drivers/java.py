"""Java task driver (reference drivers/java/driver.go).

Runs ``java [jvm_options] -jar <jar_path> [args]`` or
``java [jvm_options] -cp <class_path> <class> [args]`` through the
shared subprocess executor.  Fingerprint probes the local JVM
(reference java/driver.go Fingerprint parsing ``java -version``) and
reports the driver unhealthy when none is found.
"""
from __future__ import annotations

import re
import shutil
import subprocess
from typing import Dict

from .base import TaskConfig
from .exec import RawExecDriver

_VERSION_RE = re.compile(r'version "([^"]+)"')


class JavaDriver(RawExecDriver):
    name = "java"

    def __init__(self) -> None:
        super().__init__()
        self._java = shutil.which("java")

    def fingerprint(self) -> Dict[str, str]:
        if not self._java:
            return {f"driver.{self.name}": "0"}
        attrs = {f"driver.{self.name}": "1"}
        try:
            out = subprocess.run(
                [self._java, "-version"],
                capture_output=True, text=True, timeout=10,
            )
            # JVMs print the banner on stderr
            m = _VERSION_RE.search(out.stderr or out.stdout or "")
            if m:
                attrs[f"driver.{self.name}.version"] = m.group(1)
        except (OSError, subprocess.TimeoutExpired):
            pass
        return attrs

    def _build_command(self, cfg: TaskConfig):
        if not self._java:
            raise RuntimeError("java runtime not found on this node")
        argv = [self._java]
        argv += list(cfg.config.get("jvm_options", []))
        jar = cfg.config.get("jar_path", "")
        klass = cfg.config.get("class", "")
        if jar:
            argv += ["-jar", jar]
        elif klass:
            cp = cfg.config.get("class_path", "")
            if cp:
                argv += ["-cp", cp]
            argv.append(klass)
        else:
            raise ValueError(
                "java driver requires jar_path or class in config"
            )
        argv += list(cfg.config.get("args", []))
        return argv
