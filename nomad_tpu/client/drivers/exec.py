"""Exec drivers: run real subprocesses
(reference drivers/exec + drivers/rawexec; the reference isolates exec
tasks with libcontainer — here both variants share the subprocess
executor, with `exec` additionally entering a private working dir and a
restricted environment as the portable slice of that isolation).
"""
from __future__ import annotations

import os
import shlex
import signal as _signal
import subprocess
import threading
from typing import Dict, Optional

from .base import DriverHandle, DriverPlugin, TaskConfig, TaskExitResult


class _ProcHandle(DriverHandle):
    def __init__(self, task_id: str, proc: subprocess.Popen) -> None:
        super().__init__(task_id)
        self.proc = proc


class RawExecDriver(DriverPlugin):
    name = "raw_exec"

    def __init__(self) -> None:
        self.handles: Dict[str, _ProcHandle] = {}

    def _build_command(self, cfg: TaskConfig):
        command = cfg.config.get("command", "")
        args = cfg.config.get("args", [])
        if isinstance(args, str):
            args = shlex.split(args)
        return [command] + list(args)

    def _popen(self, cfg: TaskConfig, argv) -> subprocess.Popen:
        cwd = cfg.task_dir or cfg.alloc_dir or None
        env = dict(os.environ)
        env.update(cfg.env or {})
        return self._spawn(cfg, argv, cwd, env)

    def _spawn(self, cfg: TaskConfig, argv, cwd, env) -> subprocess.Popen:
        """Shared spawn path: logmon-rotated logs when a logs dir is
        configured (reference client/logmon), flat files otherwise."""
        if cwd:
            os.makedirs(cwd, exist_ok=True)
        if cfg.logs_dir:
            from ..logmon import LogMon

            proc = subprocess.Popen(
                argv, cwd=cwd, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True,
            )
            lm = LogMon(
                cfg.logs_dir, cfg.name,
                max_files=cfg.log_max_files,
                max_file_size_mb=cfg.log_max_file_size_mb,
            )
            lm.pump(proc.stdout, "stdout")
            lm.pump(proc.stderr, "stderr")
            # closed by the exit waiter once the pumps drain, so
            # restart loops don't leak rotator fds
            proc._logmon = lm
            return proc
        stdout = subprocess.DEVNULL
        stderr = subprocess.DEVNULL
        if cfg.alloc_dir:
            os.makedirs(cfg.alloc_dir, exist_ok=True)
            stdout = open(
                os.path.join(cfg.alloc_dir, f"{cfg.name}.stdout"), "ab"
            )
            stderr = open(
                os.path.join(cfg.alloc_dir, f"{cfg.name}.stderr"), "ab"
            )
        return subprocess.Popen(
            argv, cwd=cwd, env=env, stdout=stdout, stderr=stderr,
            start_new_session=True,
        )

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        argv = self._build_command(cfg)
        try:
            proc = self._popen(cfg, argv)
        except OSError as exc:
            raise RuntimeError(f"failed to start task: {exc}") from exc
        handle = _ProcHandle(cfg.id, proc)
        self.handles[cfg.id] = handle

        def waiter():
            code = proc.wait()
            lm = getattr(proc, "_logmon", None)
            if lm is not None:
                lm.wait(2.0)
                lm.close()
            if code < 0:
                handle.set_exit(TaskExitResult(exit_code=0, signal=-code))
            else:
                handle.set_exit(TaskExitResult(exit_code=code))

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        return handle

    def wait_task(self, task_id, timeout=None):
        handle = self.handles.get(task_id)
        if handle is None:
            return TaskExitResult(err="unknown task")
        return handle.wait(timeout)

    def stop_task(self, task_id, timeout=5.0, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        sig = getattr(_signal, signal, _signal.SIGTERM)
        try:
            os.killpg(os.getpgid(handle.proc.pid), sig)
        except ProcessLookupError:
            return
        if handle.wait(timeout) is None:
            try:
                os.killpg(os.getpgid(handle.proc.pid), _signal.SIGKILL)
            except ProcessLookupError:
                pass

    def destroy_task(self, task_id, force=False):
        handle = self.handles.get(task_id)
        if handle is not None and handle.is_running():
            if not force:
                raise RuntimeError("task is still running")
            self.stop_task(task_id, timeout=0.5, signal="SIGKILL")
        self.handles.pop(task_id, None)

    def _exec_base_env(self) -> Dict[str, str]:
        # raw_exec tasks run with the host environment, so exec
        # sessions into them do too (ExecDriver restricts this)
        return dict(os.environ)

    def exec_task(self, task_id, argv, timeout=30.0, env=None, cwd=""):
        if task_id not in self.handles:
            raise KeyError(f"unknown task {task_id!r}")
        run_env = self._exec_base_env()
        run_env.update(env or {})
        try:
            out = subprocess.run(
                list(argv),
                cwd=cwd or None,
                env=run_env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return 124, b"exec timed out"
        except OSError as exc:
            return 127, str(exc).encode()
        return out.returncode, out.stdout or b""

    def exec_task_stream(self, task_id, argv, env=None, cwd=""):
        from .base import ExecStreamHandle

        if task_id not in self.handles:
            raise KeyError(f"unknown task {task_id!r}")
        run_env = self._exec_base_env()
        run_env.update(env or {})
        return ExecStreamHandle(
            list(argv), env=run_env, cwd=cwd or None
        )

    def signal_task(self, task_id, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        name = signal if signal.startswith("SIG") else f"SIG{signal}"
        try:
            sig = _signal.Signals[name]
        except KeyError:
            raise ValueError(f"invalid signal {signal!r}")
        try:
            os.killpg(os.getpgid(handle.proc.pid), sig)
        except ProcessLookupError:
            pass

    def inspect_task(self, task_id):
        return self.handles.get(task_id)

    def recover_task(self, task_id, handle_state):
        pid = handle_state.get("pid")
        if pid is None:
            return False
        try:
            os.kill(pid, 0)
        except (ProcessLookupError, PermissionError):
            return False
        # reattach: poll the pid until it exits
        handle = DriverHandle(task_id)
        self.handles[task_id] = handle  # type: ignore[assignment]

        def poll():
            import time

            while True:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    handle.set_exit(TaskExitResult(exit_code=0))
                    return
                time.sleep(0.5)

        threading.Thread(target=poll, daemon=True).start()
        return True


class _ExecutorTaskHandle(DriverHandle):
    """Handle for a task owned by a separate executor process."""

    def __init__(self, task_id: str, client, pid: int) -> None:
        super().__init__(task_id)
        self.client = client
        self.pid = pid


class ExecDriver(RawExecDriver):
    """Isolated exec driver: each task runs under its own **executor
    process** (client/executor.py) with chroot into the task sandbox,
    a private mount namespace, and cgroup cpu/memory limits — the
    reference's libcontainer executor topology
    (drivers/shared/executor/executor_linux.go; drivers/exec).  The
    executor outlives driver restarts; reattach records let
    `recover_task` re-adopt running tasks.  Without root the executor
    process still runs (the reference keeps its executor for raw_exec
    too) but chroot/cgroups degrade to no-ops; NOMAD_TPU_EXEC_ISOLATION=0
    forces the in-process restricted-env spawn.
    """

    name = "exec"

    def __init__(self) -> None:
        super().__init__()
        self._clients: Dict[str, object] = {}

    @staticmethod
    def _use_executor() -> bool:
        import sys

        return (
            sys.platform == "linux"
            and os.environ.get("NOMAD_TPU_EXEC_ISOLATION", "1") != "0"
        )

    def _popen(self, cfg: TaskConfig, argv) -> subprocess.Popen:
        # fallback path: restricted environment, in-process spawn
        cwd = cfg.task_dir or cfg.alloc_dir or None
        env = self._exec_base_env()
        env.update(cfg.env or {})
        return self._spawn(cfg, argv, cwd, env)

    def _exec_base_env(self) -> Dict[str, str]:
        # alloc exec runs under the same restricted env as the task
        # itself — never the agent's os.environ (which may carry
        # secrets); mirrors _popen's policy
        return {"PATH": os.environ.get("PATH", "/usr/bin:/bin")}

    # -- executor-backed path ------------------------------------------

    def _log_spec(self, cfg: TaskConfig) -> Dict[str, object]:
        """Log destination part of the launch spec: rotated logmon
        pumping in the executor when a logs dir is configured, flat
        files otherwise (mirrors _spawn's policy)."""
        if cfg.logs_dir:
            os.makedirs(cfg.logs_dir, exist_ok=True)
            return {
                "logs_dir": cfg.logs_dir,
                "log_name": cfg.name,
                "log_max_files": cfg.log_max_files,
                "log_max_file_size_mb": cfg.log_max_file_size_mb,
            }
        if cfg.alloc_dir:
            return {
                "stdout_path": os.path.join(
                    cfg.alloc_dir, f"{cfg.name}.stdout"
                ),
                "stderr_path": os.path.join(
                    cfg.alloc_dir, f"{cfg.name}.stderr"
                ),
            }
        return {}

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        if not self._use_executor():
            return super().start_task(cfg)
        from .. import executor as ex

        argv = self._build_command(cfg)
        env = self._exec_base_env()
        env.update(cfg.env or {})
        chroot = ""
        populate = None
        if (
            os.geteuid() == 0
            and cfg.task_dir
            and cfg.config.get("chroot", True)
        ):
            chroot = cfg.task_dir
            # default: read-only bind mounts of the system dirs in a
            # private mount ns (reference exec driver's default chroot
            # of /bin /etc /lib /lib64 /sbin /usr); a chroot_env map
            # falls back to hardlink population
            populate = cfg.config.get("chroot_env") or "bind"
        # task-dir contract mounts: the chroot is rooted at the task's
        # local dir, so the shared alloc dir (a sibling) and the
        # secrets dir must be bind-mounted in, and the local dir
        # itself appears at /local (reference alloc_dir_linux.go
        # mountSharedDir; the executor remaps NOMAD_*_DIR to match)
        task_mounts = []
        if chroot:
            from ..allocdir import SHARED_ALLOC_NAME, TASK_SECRETS

            task_base = os.path.dirname(chroot)
            task_mounts = [
                [
                    os.path.join(
                        os.path.dirname(task_base), SHARED_ALLOC_NAME
                    ),
                    "alloc",
                ],
                [chroot, "local"],
                [os.path.join(task_base, TASK_SECRETS), "secrets"],
            ]
        res = cfg.resources
        spec = {
            "task_id": cfg.id,
            "argv": argv,
            "cwd": cfg.task_dir or cfg.alloc_dir or "",
            "env": env,
            "chroot": chroot,
            "chroot_populate": populate,
            "task_mounts": task_mounts,
            "cpu_shares": getattr(res, "cpu", 0) if res else 0,
            "memory_mb": getattr(res, "memory_mb", 0) if res else 0,
            **self._log_spec(cfg),
        }
        # a restart reuses the task id: reap the previous executor
        # before spawning the replacement, or every restart leaks one
        prev = self._clients.pop(cfg.id, None)
        if prev is not None:
            try:
                prev.destroy(cfg.id, force=True)
            except (RuntimeError, OSError):
                pass
            prev.shutdown()
        # a reused task id must not inherit the previous run's
        # persisted exit: recovery would report the STALE status for a
        # run that was actually lost mid-flight
        ex.drop_exit_record(cfg.id)
        client = ex.ExecutorClient.spawn()
        try:
            info = client.launch(spec)
        except Exception as exc:
            client.shutdown()
            raise RuntimeError(f"failed to start task: {exc}") from exc
        handle = _ExecutorTaskHandle(cfg.id, client, info["pid"])
        self.handles[cfg.id] = handle  # type: ignore[assignment]
        self._clients[cfg.id] = client
        ex.save_reattach(cfg.id, client.socket_path, info["pid"])
        self._adopt(handle)
        return handle

    def _adopt(self, handle: _ExecutorTaskHandle) -> None:
        def waiter():
            try:
                raw = handle.client.wait(handle.task_id, None)
            except (RuntimeError, OSError):
                handle.set_exit(
                    TaskExitResult(err="executor connection lost")
                )
                return
            handle.set_exit(
                TaskExitResult(
                    exit_code=int(raw.get("exit_code", 0)),
                    signal=int(raw.get("signal", 0)),
                    oom_killed=bool(raw.get("oom_killed", False)),
                    err=raw.get("err"),
                )
            )

        threading.Thread(target=waiter, daemon=True).start()

    def stop_task(self, task_id, timeout=5.0, signal="SIGTERM"):
        client = self._clients.get(task_id)
        if client is None:
            return super().stop_task(task_id, timeout, signal)
        sig = signal if signal.startswith("SIG") else f"SIG{signal}"
        try:
            client.stop(task_id, timeout=timeout, sig=sig)
        except (RuntimeError, OSError):
            pass

    def signal_task(self, task_id, signal="SIGTERM"):
        client = self._clients.get(task_id)
        if client is None:
            return super().signal_task(task_id, signal)
        name = signal if signal.startswith("SIG") else f"SIG{signal}"
        # validate client-side so invalid signals still raise like the
        # in-process path; only wire failures are swallowed
        try:
            _signal.Signals[name]
        except KeyError:
            raise ValueError(f"invalid signal {signal!r}")
        try:
            client.signal(task_id, name)
        except (RuntimeError, OSError):
            pass

    def destroy_task(self, task_id, force=False):
        client = self._clients.get(task_id)
        if client is None:
            return super().destroy_task(task_id, force)
        from .. import executor as ex

        handle = self.handles.get(task_id)
        if handle is not None and handle.is_running() and not force:
            raise RuntimeError("task is still running")
        try:
            client.destroy(task_id, force=force)
        except (RuntimeError, OSError):
            # the executor is unreachable; before discarding every
            # path to the task, make sure its process tree is dead so
            # a live task can't leak unmanaged
            if handle is not None and handle.is_running():
                try:
                    os.killpg(
                        os.getpgid(handle.pid), _signal.SIGKILL
                    )
                except (ProcessLookupError, PermissionError, OSError):
                    pass
        client.shutdown()
        ex.drop_reattach(task_id)
        self._clients.pop(task_id, None)
        self.handles.pop(task_id, None)

    def task_stats(self, task_id):
        client = self._clients.get(task_id)
        if client is None:
            return {}
        try:
            return client.stats(task_id)
        except (RuntimeError, OSError):
            return {}

    def recover_task(self, task_id, handle_state):
        if not self._use_executor():
            return super().recover_task(task_id, handle_state)
        from .. import executor as ex

        rec = ex.load_reattach(task_id)
        if rec is None:
            return super().recover_task(task_id, handle_state)

        def recovered_exit() -> bool:
            # executor gone (it self-reaps 15s after the last task
            # finishes) but the task's exit was persisted: report the
            # REAL status instead of 'lost' so a finished batch task
            # is never re-run
            raw = ex.load_exit_record(task_id)
            if raw is None:
                return False
            handle = DriverHandle(task_id)
            self.handles[task_id] = handle  # type: ignore[assignment]
            handle.set_exit(
                TaskExitResult(
                    exit_code=int(raw.get("exit_code", 0)),
                    signal=int(raw.get("signal", 0)),
                    oom_killed=bool(raw.get("oom_killed", False)),
                )
            )
            ex.drop_reattach(task_id)
            return True

        try:
            client = ex.ExecutorClient.reconnect(rec["socket"])
            tasks = {t["task_id"]: t for t in client.list_tasks()}
        except (RuntimeError, OSError):
            if recovered_exit():
                return True
            ex.drop_reattach(task_id)
            return False
        if task_id not in tasks:
            client.shutdown()
            if recovered_exit():
                return True
            ex.drop_reattach(task_id)
            return False
        handle = _ExecutorTaskHandle(
            task_id, client, tasks[task_id]["pid"]
        )
        self.handles[task_id] = handle  # type: ignore[assignment]
        self._clients[task_id] = client
        # running or already exited: wait() answers either way
        self._adopt(handle)
        return True
