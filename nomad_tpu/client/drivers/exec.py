"""Exec drivers: run real subprocesses
(reference drivers/exec + drivers/rawexec; the reference isolates exec
tasks with libcontainer — here both variants share the subprocess
executor, with `exec` additionally entering a private working dir and a
restricted environment as the portable slice of that isolation).
"""
from __future__ import annotations

import os
import shlex
import signal as _signal
import subprocess
import threading
from typing import Dict, Optional

from .base import DriverHandle, DriverPlugin, TaskConfig, TaskExitResult


class _ProcHandle(DriverHandle):
    def __init__(self, task_id: str, proc: subprocess.Popen) -> None:
        super().__init__(task_id)
        self.proc = proc


class RawExecDriver(DriverPlugin):
    name = "raw_exec"

    def __init__(self) -> None:
        self.handles: Dict[str, _ProcHandle] = {}

    def _build_command(self, cfg: TaskConfig):
        command = cfg.config.get("command", "")
        args = cfg.config.get("args", [])
        if isinstance(args, str):
            args = shlex.split(args)
        return [command] + list(args)

    def _popen(self, cfg: TaskConfig, argv) -> subprocess.Popen:
        cwd = cfg.task_dir or cfg.alloc_dir or None
        env = dict(os.environ)
        env.update(cfg.env or {})
        return self._spawn(cfg, argv, cwd, env)

    def _spawn(self, cfg: TaskConfig, argv, cwd, env) -> subprocess.Popen:
        """Shared spawn path: logmon-rotated logs when a logs dir is
        configured (reference client/logmon), flat files otherwise."""
        if cwd:
            os.makedirs(cwd, exist_ok=True)
        if cfg.logs_dir:
            from ..logmon import LogMon

            proc = subprocess.Popen(
                argv, cwd=cwd, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True,
            )
            lm = LogMon(
                cfg.logs_dir, cfg.name,
                max_files=cfg.log_max_files,
                max_file_size_mb=cfg.log_max_file_size_mb,
            )
            lm.pump(proc.stdout, "stdout")
            lm.pump(proc.stderr, "stderr")
            # closed by the exit waiter once the pumps drain, so
            # restart loops don't leak rotator fds
            proc._logmon = lm
            return proc
        stdout = subprocess.DEVNULL
        stderr = subprocess.DEVNULL
        if cfg.alloc_dir:
            os.makedirs(cfg.alloc_dir, exist_ok=True)
            stdout = open(
                os.path.join(cfg.alloc_dir, f"{cfg.name}.stdout"), "ab"
            )
            stderr = open(
                os.path.join(cfg.alloc_dir, f"{cfg.name}.stderr"), "ab"
            )
        return subprocess.Popen(
            argv, cwd=cwd, env=env, stdout=stdout, stderr=stderr,
            start_new_session=True,
        )

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        argv = self._build_command(cfg)
        try:
            proc = self._popen(cfg, argv)
        except OSError as exc:
            raise RuntimeError(f"failed to start task: {exc}") from exc
        handle = _ProcHandle(cfg.id, proc)
        self.handles[cfg.id] = handle

        def waiter():
            code = proc.wait()
            lm = getattr(proc, "_logmon", None)
            if lm is not None:
                lm.wait(2.0)
                lm.close()
            if code < 0:
                handle.set_exit(TaskExitResult(exit_code=0, signal=-code))
            else:
                handle.set_exit(TaskExitResult(exit_code=code))

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        return handle

    def wait_task(self, task_id, timeout=None):
        handle = self.handles.get(task_id)
        if handle is None:
            return TaskExitResult(err="unknown task")
        return handle.wait(timeout)

    def stop_task(self, task_id, timeout=5.0, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        sig = getattr(_signal, signal, _signal.SIGTERM)
        try:
            os.killpg(os.getpgid(handle.proc.pid), sig)
        except ProcessLookupError:
            return
        if handle.wait(timeout) is None:
            try:
                os.killpg(os.getpgid(handle.proc.pid), _signal.SIGKILL)
            except ProcessLookupError:
                pass

    def destroy_task(self, task_id, force=False):
        handle = self.handles.get(task_id)
        if handle is not None and handle.is_running():
            if not force:
                raise RuntimeError("task is still running")
            self.stop_task(task_id, timeout=0.5, signal="SIGKILL")
        self.handles.pop(task_id, None)

    def _exec_base_env(self) -> Dict[str, str]:
        # raw_exec tasks run with the host environment, so exec
        # sessions into them do too (ExecDriver restricts this)
        return dict(os.environ)

    def exec_task(self, task_id, argv, timeout=30.0, env=None, cwd=""):
        if task_id not in self.handles:
            raise KeyError(f"unknown task {task_id!r}")
        run_env = self._exec_base_env()
        run_env.update(env or {})
        try:
            out = subprocess.run(
                list(argv),
                cwd=cwd or None,
                env=run_env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return 124, b"exec timed out"
        except OSError as exc:
            return 127, str(exc).encode()
        return out.returncode, out.stdout or b""

    def signal_task(self, task_id, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        name = signal if signal.startswith("SIG") else f"SIG{signal}"
        try:
            sig = _signal.Signals[name]
        except KeyError:
            raise ValueError(f"invalid signal {signal!r}")
        try:
            os.killpg(os.getpgid(handle.proc.pid), sig)
        except ProcessLookupError:
            pass

    def inspect_task(self, task_id):
        return self.handles.get(task_id)

    def recover_task(self, task_id, handle_state):
        pid = handle_state.get("pid")
        if pid is None:
            return False
        try:
            os.kill(pid, 0)
        except (ProcessLookupError, PermissionError):
            return False
        # reattach: poll the pid until it exits
        handle = DriverHandle(task_id)
        self.handles[task_id] = handle  # type: ignore[assignment]

        def poll():
            import time

            while True:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    handle.set_exit(TaskExitResult(exit_code=0))
                    return
                time.sleep(0.5)

        threading.Thread(target=poll, daemon=True).start()
        return True


class ExecDriver(RawExecDriver):
    name = "exec"

    def _popen(self, cfg: TaskConfig, argv) -> subprocess.Popen:
        # restricted environment: only the task's own env plus PATH
        cwd = cfg.task_dir or cfg.alloc_dir or None
        env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin")}
        env.update(cfg.env or {})
        return self._spawn(cfg, argv, cwd, env)

    def _exec_base_env(self) -> Dict[str, str]:
        # alloc exec runs under the same restricted env as the task
        # itself — never the agent's os.environ (which may carry
        # secrets); mirrors _popen's policy
        return {"PATH": os.environ.get("PATH", "/usr/bin:/bin")}
