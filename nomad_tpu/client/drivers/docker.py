"""Docker container driver over the daemon's HTTP API
(reference drivers/docker/driver.go + the docklog companion).

Talks to dockerd's Engine API on the unix socket directly — create/
start/stop/kill/wait/inspect/stats/exec ride
``/containers/...``/``/exec/...`` exactly like the reference's
go-dockerclient does; nothing shells out to the docker CLI.  Each
started container gets a **docklog companion thread** (reference
drivers/docker/docklog: a sidecar streaming the container's log
endpoint) that demuxes the attach-stream frames into the task's
logmon rotators, so `alloc logs`/`logs -f` read docker tasks through
the exact same path as exec tasks.

The socket path comes from ``DOCKER_HOST`` (``unix://...`` form) or
defaults to ``/var/run/docker.sock``; tests point it at a mock daemon.
"""
from __future__ import annotations

import http.client
import json
import os
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from .base import (
    DriverHandle,
    DriverPlugin,
    TaskConfig,
    TaskExitResult,
)

_API = "/v1.40"  # stable floor the calls below all exist in


class _UnixHTTPConnection(http.client.HTTPConnection):
    """http.client over a unix domain socket (the Engine API's
    default transport)."""

    def __init__(self, sock_path: str, timeout=30.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._sock_path)
        self.sock = s


class DockerAPIError(RuntimeError):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"docker API {status}: {message}")
        self.status = status


class DockerAPI:
    """Minimal Engine API client: the endpoints the driver lifecycle,
    stats/events observability and the docklog companion need."""

    def __init__(self, sock_path: str) -> None:
        self.sock_path = sock_path

    def _request(
        self, method: str, path: str, body=None,
        timeout: float = 30.0,
    ):
        conn = _UnixHTTPConnection(self.sock_path, timeout=timeout)
        try:
            data = (
                json.dumps(body).encode()
                if body is not None
                else None
            )
            headers = {"Host": "docker"}
            if data is not None:
                headers["Content-Type"] = "application/json"
            conn.request(
                method, _API + path, body=data, headers=headers
            )
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status >= 400:
                msg = ""
                try:
                    msg = json.loads(raw).get("message", "")
                except Exception:  # noqa: BLE001
                    msg = raw.decode(errors="replace")[:200]
                raise DockerAPIError(resp.status, msg)
            if not raw:
                return None
            try:
                return json.loads(raw)
            except ValueError:
                return raw
        finally:
            conn.close()

    # -- lifecycle -----------------------------------------------------

    def version(self):
        return self._request("GET", "/version", timeout=5.0)

    def create_container(self, name: str, spec: dict) -> str:
        out = self._request(
            "POST", f"/containers/create?name={name}", spec
        )
        return out["Id"]

    def start_container(self, cid: str) -> None:
        self._request("POST", f"/containers/{cid}/start", {})

    def stop_container(self, cid: str, timeout_s: int) -> None:
        self._request(
            "POST",
            f"/containers/{cid}/stop?t={int(timeout_s)}",
            timeout=timeout_s + 15.0,
        )

    def kill_container(self, cid: str, signal: str) -> None:
        self._request(
            "POST", f"/containers/{cid}/kill?signal={signal}"
        )

    def remove_container(self, cid: str, force: bool = True) -> None:
        self._request(
            "DELETE",
            f"/containers/{cid}?force={'true' if force else 'false'}",
        )

    def wait_container(self, cid: str) -> int:
        """Blocks until the container exits (long request, like the
        reference's WaitContainer)."""
        out = self._request(
            "POST", f"/containers/{cid}/wait", timeout=86400.0
        )
        return int(out.get("StatusCode", 0))

    def inspect_container(self, cid: str):
        return self._request("GET", f"/containers/{cid}/json")

    def pull_image(self, image: str) -> None:
        """POST /images/create streams progress JSON; drain it."""
        conn = _UnixHTTPConnection(self.sock_path, timeout=600.0)
        try:
            tag = "latest"
            name = image
            if ":" in image.rsplit("/", 1)[-1]:
                name, tag = image.rsplit(":", 1)
            conn.request(
                "POST",
                f"{_API}/images/create?fromImage={name}&tag={tag}",
                headers={"Host": "docker"},
            )
            resp = conn.getresponse()
            # the daemon reports pull failures as 200 + progress
            # lines carrying errorDetail — scan, don't just drain
            tail = b""
            err = ""
            while True:
                chunk = resp.read(65536)
                if not chunk:
                    break
                tail = (tail + chunk)[-65536:]
            for line in tail.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("error") or rec.get("errorDetail"):
                    err = rec.get("error") or str(
                        rec["errorDetail"]
                    )
            if resp.status >= 400:
                raise DockerAPIError(resp.status, "image pull failed")
            if err:
                raise DockerAPIError(500, f"image pull: {err}")
        finally:
            conn.close()

    # -- observability -------------------------------------------------

    def stats(self, cid: str):
        """One-shot container stats (reference DriverStats)."""
        return self._request(
            "GET", f"/containers/{cid}/stats?stream=false"
        )

    def events(self, since: int, until: int):
        """Container events in a window (reference TaskEvents)."""
        raw = self._request(
            "GET", f"/events?since={since}&until={until}",
            timeout=10.0,
        )
        if isinstance(raw, (bytes, bytearray)):
            out = []
            for line in raw.splitlines():
                if line.strip():
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
            return out
        return [raw] if raw else []

    # -- exec ----------------------------------------------------------

    def exec_in_container(
        self, cid: str, argv, timeout: float = 30.0
    ) -> Tuple[int, bytes]:
        out = self._request(
            "POST",
            f"/containers/{cid}/exec",
            {
                "AttachStdout": True,
                "AttachStderr": True,
                "Cmd": list(argv),
            },
        )
        exec_id = out["Id"]
        conn = _UnixHTTPConnection(self.sock_path, timeout=timeout)
        try:
            conn.request(
                "POST",
                f"{_API}/exec/{exec_id}/start",
                body=json.dumps(
                    {"Detach": False, "Tty": False}
                ).encode(),
                headers={
                    "Host": "docker",
                    "Content-Type": "application/json",
                },
            )
            resp = conn.getresponse()
            raw = resp.read()
        finally:
            conn.close()
        output = b"".join(
            payload for _stream, payload in _demux_frames(raw)
        )
        ins = self._request("GET", f"/exec/{exec_id}/json")
        return int(ins.get("ExitCode") or 0), output

    # -- docklog -------------------------------------------------------

    def stream_logs(self, cid: str, on_frame, stop_event) -> None:
        """Follow the container's log endpoint and hand each demuxed
        (stream, payload) frame to ``on_frame`` until EOF or stop —
        the transport half of the docklog companion.

        Reads BLOCK with no socket timeout: a timeout firing mid-chunk
        would leave http.client's chunked-decoder state undefined and
        mis-frame everything after.  Stop is delivered by closing the
        socket from a watchdog thread instead."""
        conn = _UnixHTTPConnection(self.sock_path, timeout=None)
        closed = threading.Event()

        def closer() -> None:
            stop_event.wait()
            if not closed.is_set():
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass

        threading.Thread(target=closer, daemon=True).start()
        try:
            conn.request(
                "GET",
                f"{_API}/containers/{cid}/logs"
                "?follow=true&stdout=true&stderr=true",
                headers={"Host": "docker"},
            )
            resp = conn.getresponse()
            buf = b""
            while not stop_event.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    break
                buf += chunk
                frames, buf = _split_frames(buf)
                for stream, payload in frames:
                    on_frame(stream, payload)
        except (OSError, ValueError, http.client.HTTPException):
            pass
        finally:
            closed.set()
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass


def _split_frames(buf: bytes):
    """Split complete attach-stream frames off the front of ``buf``
    (Engine API stream format: 8-byte header = stream byte, 3 zero
    bytes, u32 big-endian length)."""
    frames = []
    while len(buf) >= 8:
        stream = buf[0]
        (length,) = struct.unpack(">I", buf[4:8])
        if len(buf) < 8 + length:
            break
        frames.append((stream, buf[8 : 8 + length]))
        buf = buf[8 + length :]
    return frames, buf


def _demux_frames(raw: bytes):
    frames, _rest = _split_frames(raw)
    return frames


def _docker_host() -> str:
    host = os.environ.get("DOCKER_HOST", "")
    if host.startswith("unix://"):
        return host[len("unix://"):]
    if host:
        return host
    return "/var/run/docker.sock"


class _ContainerHandle(DriverHandle):
    def __init__(self, task_id: str, container: str) -> None:
        super().__init__(task_id)
        self.container = container


class DockerDriver(DriverPlugin):
    name = "docker"

    # how long a daemon probe result stays fresh; the reference
    # re-fingerprints drivers periodically so a daemon that starts or
    # dies after agent boot flips the node's driver attribute
    PROBE_TTL = 30.0

    def __init__(self, sock_path: Optional[str] = None) -> None:
        self.api = DockerAPI(sock_path or _docker_host())
        self.handles: Dict[str, _ContainerHandle] = {}
        self._daemon_ok: Optional[bool] = None
        self._probed_at = 0.0
        self._server_version = ""
        self._docklogs: Dict[str, threading.Event] = {}

    # ------------------------------------------------------------------

    def _daemon_reachable(self) -> bool:
        import time

        now = time.monotonic()
        if (
            self._daemon_ok is None
            or now - self._probed_at >= self.PROBE_TTL
        ):
            self._probed_at = now
            try:
                v = self.api.version()
                self._daemon_ok = True
                self._server_version = (v or {}).get("Version", "")
            except Exception:  # noqa: BLE001
                self._daemon_ok = False
        return bool(self._daemon_ok)

    def fingerprint(self) -> Dict[str, str]:
        if not self._daemon_reachable():
            return {f"driver.{self.name}": "0"}
        attrs = {f"driver.{self.name}": "1"}
        if self._server_version:
            attrs[f"driver.{self.name}.version"] = self._server_version
        return attrs

    # ------------------------------------------------------------------

    def _container_spec(self, cfg: TaskConfig) -> dict:
        image = cfg.config.get("image", "")
        if not image:
            raise ValueError("docker driver requires image in config")
        binds = []
        if cfg.alloc_dir:
            binds.append(f"{cfg.alloc_dir}:/alloc")
        binds.extend(cfg.config.get("volumes", []) or [])
        port_bindings = {}
        for guest, host in (
            cfg.config.get("port_map", {}) or {}
        ).items():
            port_bindings[f"{guest}/tcp"] = [
                {"HostPort": str(host)}
            ]
        cmd = []
        command = cfg.config.get("command", "")
        if command:
            cmd.append(command)
        cmd.extend(cfg.config.get("args", []) or [])
        spec = {
            "Image": image,
            "Env": [
                f"{k}={v}" for k, v in (cfg.env or {}).items()
            ],
            "Labels": {
                "nomad.task_id": cfg.id,
                "nomad.alloc_id": cfg.alloc_id,
            },
            "HostConfig": {
                "Binds": binds,
                "PortBindings": port_bindings,
                "AutoRemove": False,
            },
        }
        if cmd:
            spec["Cmd"] = cmd
        if cfg.resources is not None and cfg.resources.memory_mb:
            spec["HostConfig"]["Memory"] = (
                int(cfg.resources.memory_mb) * 1024 * 1024
            )
        return spec

    def _start_docklog(
        self, task_id: str, task_name: str, cid: str,
        log_dir: str, max_files: int, max_size_mb: int,
    ) -> None:
        """The docklog companion (reference drivers/docker/docklog):
        stream the container's logs into the task's logmon rotators so
        `alloc logs`/`logs -f` serve docker tasks like any other."""
        from ..logmon import LogMon

        if not log_dir:
            return
        lm = LogMon(
            log_dir, task_name,
            max_files=max_files,
            max_file_size_mb=max_size_mb,
        )
        stop = threading.Event()
        drained = threading.Event()
        self._docklogs[task_id] = (stop, drained)

        def on_frame(stream: int, payload: bytes) -> None:
            (lm.stderr if stream == 2 else lm.stdout).write(payload)

        def run() -> None:
            try:
                self.api.stream_logs(cid, on_frame, stop)
            finally:
                lm.close()
                drained.set()

        threading.Thread(
            target=run, name=f"docklog-{task_name}", daemon=True
        ).start()

    def _finish_docklog(self, task_id: str) -> None:
        """Give the companion a grace window to drain to EOF, then
        stop it as a backstop (a wedged daemon connection must not
        pin the waiter)."""
        entry = self._docklogs.pop(task_id, None)
        if entry is None:
            return
        stop, drained = entry
        drained.wait(timeout=2.0)
        stop.set()

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        if not self._daemon_reachable():
            raise RuntimeError(
                "docker daemon not reachable on this node"
            )
        container = f"nomad-{cfg.id}".replace("/", "-")
        spec = self._container_spec(cfg)

        def create():
            try:
                return self.api.create_container(container, spec)
            except DockerAPIError as exc:
                if exc.status == 409:
                    # a previous run's exited container still holds
                    # the name (restart loop): clear it and retry —
                    # the CLI's --rm used to free the name on exit
                    self.api.remove_container(
                        container, force=True
                    )
                    return self.api.create_container(
                        container, spec
                    )
                raise

        try:
            cid = create()
        except DockerAPIError as exc:
            if exc.status != 404:
                raise
            # image missing locally: pull then retry (reference
            # driver's CreateImage path)
            self.api.pull_image(spec["Image"])
            cid = create()
        self.api.start_container(cid)
        handle = _ContainerHandle(cfg.id, cid)
        log_dir = cfg.logs_dir or (
            os.path.join(cfg.alloc_dir, "alloc", "logs")
            if cfg.alloc_dir
            else ""
        )
        # persisted with the task snapshot so a restarted client can
        # reattach the docklog companion, not just the wait loop
        handle.docklog_state = {
            "logs_dir": log_dir,
            "task_name": cfg.name,
            "log_max_files": cfg.log_max_files,
            "log_max_file_size_mb": cfg.log_max_file_size_mb,
        }
        self.handles[cfg.id] = handle
        self._start_docklog(
            cfg.id, cfg.name, cid, log_dir,
            cfg.log_max_files, cfg.log_max_file_size_mb,
        )

        def waiter():
            try:
                code = self.api.wait_container(cid)
            except Exception:  # noqa: BLE001
                code = -1
            # drain the docklog BEFORE cutting it: the daemon closes
            # the follow stream at container exit, so the companion
            # reaches EOF on its own — stopping it immediately would
            # drop the task's final buffered frames from the rotators
            self._finish_docklog(cfg.id)
            handle.set_exit(TaskExitResult(exit_code=code))
            # emulate the CLI path's --rm: the exited container's
            # logs already live in the rotators, so free the name and
            # the disk for the restart loop
            try:
                self.api.remove_container(cid, force=True)
            except (DockerAPIError, OSError):
                pass

        threading.Thread(target=waiter, daemon=True).start()
        return handle

    def wait_task(self, task_id, timeout=None):
        handle = self.handles.get(task_id)
        if handle is None:
            return TaskExitResult(err="unknown task")
        return handle.wait(timeout)

    def stop_task(self, task_id, timeout=5.0, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        try:
            self.api.stop_container(
                handle.container, int(timeout)
            )
        except (DockerAPIError, OSError):
            pass

    def exec_task(self, task_id, argv, timeout=30.0, env=None, cwd=""):
        handle = self.handles.get(task_id)
        if handle is None:
            raise KeyError(f"unknown task {task_id!r}")
        try:
            return self.api.exec_in_container(
                handle.container, argv, timeout=timeout
            )
        except (TimeoutError, socket.timeout):
            return 124, b"exec timed out"
        except (DockerAPIError, OSError) as exc:
            return 127, str(exc).encode()

    def signal_task(self, task_id, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        try:
            self.api.kill_container(
                handle.container, signal.replace("SIG", "")
            )
        except (DockerAPIError, OSError):
            pass

    def task_stats(self, task_id):
        """One-shot stats from the daemon (reference TaskStats)."""
        handle = self.handles.get(task_id)
        if handle is None:
            raise KeyError(f"unknown task {task_id!r}")
        return self.api.stats(handle.container)

    def destroy_task(self, task_id, force=False):
        handle = self.handles.get(task_id)
        if handle is not None and handle.is_running():
            if not force:
                raise RuntimeError("task is still running")
            try:
                self.api.remove_container(
                    handle.container, force=True
                )
            except (DockerAPIError, OSError):
                pass
        entry = self._docklogs.pop(task_id, None)
        if entry is not None:
            entry[0].set()
        self.handles.pop(task_id, None)

    def inspect_task(self, task_id):
        return self.handles.get(task_id)

    def handle_state(self, task_id: str) -> Dict:
        handle = self.handles.get(task_id)
        if handle is None:
            return {}
        out = {"container": handle.container}
        out.update(getattr(handle, "docklog_state", {}))
        return out

    def recover_task(self, task_id, handle_state) -> bool:
        container = handle_state.get("container", "")
        if not container or not self._daemon_reachable():
            return False
        try:
            ins = self.api.inspect_container(container)
        except (DockerAPIError, OSError):
            return False
        if not (ins.get("State") or {}).get("Running"):
            return False
        handle = _ContainerHandle(task_id, container)
        handle.docklog_state = {
            k: handle_state[k]
            for k in (
                "logs_dir", "task_name", "log_max_files",
                "log_max_file_size_mb",
            )
            if k in handle_state
        }
        self.handles[task_id] = handle
        # reattach the docklog companion too — without it a recovered
        # task's logs silently stop flowing into the rotators
        if handle.docklog_state.get("logs_dir"):
            self._start_docklog(
                task_id,
                handle.docklog_state.get("task_name", "task"),
                container,
                handle.docklog_state["logs_dir"],
                int(handle.docklog_state.get("log_max_files", 10)),
                int(
                    handle.docklog_state.get(
                        "log_max_file_size_mb", 10
                    )
                ),
            )

        def poll():
            try:
                code = self.api.wait_container(container)
            except Exception:  # noqa: BLE001
                code = 0
            self._finish_docklog(task_id)
            handle.set_exit(TaskExitResult(exit_code=code))
            try:
                self.api.remove_container(container, force=True)
            except (DockerAPIError, OSError):
                pass

        threading.Thread(target=poll, daemon=True).start()
        return True
