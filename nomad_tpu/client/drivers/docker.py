"""Docker container driver (reference drivers/docker/driver.go).

Runs containers through the docker CLI as the portable seam (the
reference talks to dockerd's API socket; the lifecycle mapping is the
same): ``start`` = ``docker run`` with name/env/volume/port wiring,
``stop`` = ``docker stop -t <kill_timeout>``, ``destroy`` =
``docker rm -f``.  Fingerprint probes the daemon and reports the driver
unhealthy when unreachable, so placement simply skips docker tasks on
nodes without a daemon (feasibility via DriverChecker).
"""
from __future__ import annotations

import shutil
import subprocess
import threading
from typing import Dict, Optional

from .base import (
    DriverHandle,
    DriverPlugin,
    TaskConfig,
    TaskExitResult,
)


class _ContainerHandle(DriverHandle):
    def __init__(self, task_id: str, container: str) -> None:
        super().__init__(task_id)
        self.container = container


class DockerDriver(DriverPlugin):
    name = "docker"

    # how long a daemon probe result stays fresh; the reference
    # re-fingerprints drivers periodically so a daemon that starts or
    # dies after agent boot flips the node's driver attribute
    PROBE_TTL = 30.0

    def __init__(self) -> None:
        self._docker = shutil.which("docker")
        self.handles: Dict[str, _ContainerHandle] = {}
        self._daemon_ok: Optional[bool] = None
        self._probed_at = 0.0

    # ------------------------------------------------------------------

    def _daemon_reachable(self) -> bool:
        import time

        now = time.monotonic()
        if (
            self._daemon_ok is None
            or now - self._probed_at >= self.PROBE_TTL
        ):
            self._probed_at = now
            if not self._docker:
                self._daemon_ok = False
            else:
                try:
                    out = subprocess.run(
                        [self._docker, "version", "--format",
                         "{{.Server.Version}}"],
                        capture_output=True, text=True, timeout=5,
                    )
                    self._daemon_ok = out.returncode == 0
                    self._server_version = (out.stdout or "").strip()
                except (OSError, subprocess.TimeoutExpired):
                    self._daemon_ok = False
        return bool(self._daemon_ok)

    def fingerprint(self) -> Dict[str, str]:
        if not self._daemon_reachable():
            return {f"driver.{self.name}": "0"}
        attrs = {f"driver.{self.name}": "1"}
        if getattr(self, "_server_version", ""):
            attrs[f"driver.{self.name}.version"] = self._server_version
        return attrs

    # ------------------------------------------------------------------

    def _run_argv(self, cfg: TaskConfig, container: str):
        image = cfg.config.get("image", "")
        if not image:
            raise ValueError("docker driver requires image in config")
        argv = [self._docker, "run", "--rm", "--name", container]
        for k, v in (cfg.env or {}).items():
            argv += ["-e", f"{k}={v}"]
        if cfg.resources is not None:
            argv += ["--memory", f"{cfg.resources.memory_mb}m"]
        if cfg.alloc_dir:
            argv += ["-v", f"{cfg.alloc_dir}:/alloc"]
        for vol in cfg.config.get("volumes", []) or []:
            argv += ["-v", vol]
        port_map = cfg.config.get("port_map", {}) or {}
        for guest, host in port_map.items():
            argv += ["-p", f"{host}:{guest}"]
        argv.append(image)
        command = cfg.config.get("command", "")
        if command:
            argv.append(command)
        argv += list(cfg.config.get("args", []))
        return argv

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        if not self._daemon_reachable():
            raise RuntimeError("docker daemon not reachable on this node")
        container = f"nomad-{cfg.id}".replace("/", "-")
        argv = self._run_argv(cfg, container)
        proc = subprocess.Popen(
            argv,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        handle = _ContainerHandle(cfg.id, container)
        handle.proc = proc
        self.handles[cfg.id] = handle

        def waiter():
            code = proc.wait()
            handle.set_exit(TaskExitResult(exit_code=code))

        threading.Thread(target=waiter, daemon=True).start()
        return handle

    def wait_task(self, task_id, timeout=None):
        handle = self.handles.get(task_id)
        if handle is None:
            return TaskExitResult(err="unknown task")
        return handle.wait(timeout)

    def stop_task(self, task_id, timeout=5.0, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        try:
            subprocess.run(
                [self._docker, "stop", "-t", str(int(timeout)),
                 handle.container],
                capture_output=True, timeout=timeout + 10,
            )
        except (OSError, subprocess.TimeoutExpired):
            pass

    def exec_task(self, task_id, argv, timeout=30.0, env=None, cwd=""):
        handle = self.handles.get(task_id)
        if handle is None:
            raise KeyError(f"unknown task {task_id!r}")
        try:
            out = subprocess.run(
                [self._docker, "exec", handle.container] + list(argv),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return 124, b"exec timed out"
        except OSError as exc:
            return 127, str(exc).encode()
        return out.returncode, out.stdout or b""

    def signal_task(self, task_id, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None or not handle.is_running():
            return
        try:
            subprocess.run(
                [self._docker, "kill", "-s", signal.replace("SIG", ""),
                 handle.container],
                capture_output=True, timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            pass

    def destroy_task(self, task_id, force=False):
        handle = self.handles.get(task_id)
        if handle is not None and handle.is_running():
            if not force:
                raise RuntimeError("task is still running")
            try:
                subprocess.run(
                    [self._docker, "rm", "-f", handle.container],
                    capture_output=True, timeout=30,
                )
            except (OSError, subprocess.TimeoutExpired):
                pass
        self.handles.pop(task_id, None)

    def inspect_task(self, task_id):
        return self.handles.get(task_id)

    def recover_task(self, task_id, handle_state) -> bool:
        container = handle_state.get("container", "")
        if not container or not self._daemon_reachable():
            return False
        try:
            out = subprocess.run(
                [self._docker, "inspect", "--format",
                 "{{.State.Running}}", container],
                capture_output=True, text=True, timeout=5,
            )
        except (OSError, subprocess.TimeoutExpired):
            return False
        if out.returncode != 0 or "true" not in out.stdout:
            return False
        handle = _ContainerHandle(task_id, container)
        self.handles[task_id] = handle

        def poll():
            code = 0
            try:
                out = subprocess.run(
                    [self._docker, "wait", container],
                    capture_output=True, text=True, timeout=None,
                )
                code = int((out.stdout or "0").strip() or 0)
            except (OSError, ValueError):
                pass
            handle.set_exit(TaskExitResult(exit_code=code))

        threading.Thread(target=poll, daemon=True).start()
        return True
