"""External (out-of-process) driver plugins (reference plugins/base/
plugin.go + plugins/drivers: every driver is a separate go-plugin
process speaking gRPC over a unix socket).

Same topology here, over the framed wire protocol (nomad_tpu/wire.py —
the seam native/wire.{h,cpp} implements in C++, so plugins can be
written in any language that frames msgpack-compatible messages):

* **Host side** — `ExternalDriver` launches the plugin command, reads
  the go-plugin-style handshake line ``1|1|unix|<socket path>|wire``
  from its stdout, connects, and proxies the `DriverPlugin` surface as
  wire calls.
* **Plugin side** — `serve_plugin(driver)` wraps any in-process
  `DriverPlugin` implementation as a plugin process: binds the socket,
  prints the handshake, and dispatches calls.  `python -m
  nomad_tpu.client.drivers.external <driver>` serves a builtin driver
  this way (the loopback equivalent of go-plugin's internal drivers —
  and the test fixture).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
from typing import Any, Dict, Optional

from ...wire import call, decode, encode, recv_frame, send_frame
from .base import (
    DriverHandle,
    DriverPlugin,
    RecoverableError,
    TaskConfig,
    TaskExitResult,
)

HANDSHAKE_CORE = 1
HANDSHAKE_PROTO = 1


def _cfg_to_wire(cfg: TaskConfig) -> Dict[str, Any]:
    res = None
    if cfg.resources is not None:
        res = {
            "cpu": getattr(cfg.resources, "cpu", 0),
            "memory_mb": getattr(cfg.resources, "memory_mb", 0),
            "disk_mb": getattr(cfg.resources, "disk_mb", 0),
        }
    return {
        "id": cfg.id,
        "name": cfg.name,
        "alloc_id": cfg.alloc_id,
        "config": cfg.config,
        "env": cfg.env,
        "alloc_dir": cfg.alloc_dir,
        "task_dir": cfg.task_dir,
        "logs_dir": cfg.logs_dir,
        "log_max_files": cfg.log_max_files,
        "log_max_file_size_mb": cfg.log_max_file_size_mb,
        "resources": res,
    }


def _cfg_from_wire(raw: Dict[str, Any]) -> TaskConfig:
    cfg = TaskConfig(
        id=raw.get("id", ""),
        name=raw.get("name", ""),
        alloc_id=raw.get("alloc_id", ""),
        config=raw.get("config") or {},
        env=raw.get("env") or {},
        alloc_dir=raw.get("alloc_dir", ""),
        task_dir=raw.get("task_dir", ""),
        logs_dir=raw.get("logs_dir", ""),
        log_max_files=int(raw.get("log_max_files", 10)),
        log_max_file_size_mb=int(raw.get("log_max_file_size_mb", 10)),
    )
    res = raw.get("resources")
    if res:
        from ...structs import Resources

        cfg.resources = Resources(
            cpu=int(res.get("cpu", 0)),
            memory_mb=int(res.get("memory_mb", 0)),
            disk_mb=int(res.get("disk_mb", 0)),
        )
    return cfg


def _result_to_wire(r: Optional[TaskExitResult]):
    if r is None:
        return None
    return {
        "exit_code": r.exit_code,
        "signal": r.signal,
        "oom_killed": r.oom_killed,
        "err": r.err,
    }


def _result_from_wire(raw) -> Optional[TaskExitResult]:
    if raw is None:
        return None
    return TaskExitResult(
        exit_code=int(raw.get("exit_code", 0)),
        signal=int(raw.get("signal", 0)),
        oom_killed=bool(raw.get("oom_killed", False)),
        err=raw.get("err"),
    )


class ExternalDriver(DriverPlugin):
    """Proxy to a driver plugin process (reference plugins/drivers
    gRPC client; lifecycle per go-plugin: spawn, handshake, dial)."""

    name = "external"

    HANDSHAKE_TIMEOUT = 10.0
    # slack past the logical call timeout before declaring the stream
    # dead; the protocol has no request IDs, so a timed-out call
    # poisons the connection (a late reply would answer the wrong
    # request otherwise)
    CALL_GRACE = 15.0

    def __init__(self, plugin_cmd, name: str = "") -> None:
        if name:
            self.name = name
        self._lock = threading.Lock()
        self._broken = False
        self.proc = subprocess.Popen(
            list(plugin_cmd),
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        line = self._read_handshake(plugin_cmd)
        parts = line.split("|")
        if len(parts) != 5 or parts[2] != "unix":
            self.proc.kill()
            raise RuntimeError(
                f"bad plugin handshake from {plugin_cmd!r}: {line!r}"
            )
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(30.0 + self.CALL_GRACE)
        self.sock.connect(parts[3])

    def _read_handshake(self, plugin_cmd) -> str:
        """Bounded handshake read (go-plugin kills plugins that don't
        handshake in time)."""
        result: Dict[str, str] = {}

        def read():
            result["line"] = (
                self.proc.stdout.readline() or ""
            ).strip()

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(self.HANDSHAKE_TIMEOUT)
        if t.is_alive():
            self.proc.kill()
            raise RuntimeError(
                f"plugin {plugin_cmd!r} did not handshake within "
                f"{self.HANDSHAKE_TIMEOUT}s"
            )
        return result.get("line", "")

    def _call(
        self, method: str, body: Any, timeout: Optional[float] = 30.0
    ) -> Any:
        with self._lock:
            if self._broken:
                raise RuntimeError(
                    "plugin connection is poisoned by an earlier "
                    "timeout; restart the plugin"
                )
            self.sock.settimeout(
                None if timeout is None else timeout + self.CALL_GRACE
            )
            try:
                resp = call(self.sock, method, body)
            except socket.timeout:
                self._broken = True
                try:
                    self.sock.close()
                except OSError:
                    pass
                raise RuntimeError(
                    f"plugin call {method} timed out; connection "
                    "poisoned"
                )
        if isinstance(resp, dict) and resp.get("error"):
            err = resp["error"]
            if resp.get("recoverable"):
                raise RecoverableError(err)
            raise RuntimeError(err)
        return resp

    # -- DriverPlugin surface ------------------------------------------

    def fingerprint(self) -> Dict[str, str]:
        return self._call("Fingerprint", {}) or {}

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        self._call("StartTask", _cfg_to_wire(cfg))
        return DriverHandle(cfg.id)

    # wait_task(timeout=None) polls in bounded slices: the wire
    # protocol is single-in-flight under _lock, so one unbounded
    # WaitTask would block stop/signal/exec for every task on the
    # plugin; slicing releases the lock between polls
    WAIT_SLICE = 1.0

    def wait_task(self, task_id, timeout=None):
        if timeout is not None:
            return _result_from_wire(
                self._call(
                    "WaitTask",
                    {"task_id": task_id, "timeout": timeout},
                    timeout=timeout,
                )
            )
        while True:
            raw = self._call(
                "WaitTask",
                {"task_id": task_id, "timeout": self.WAIT_SLICE},
                timeout=self.WAIT_SLICE,
            )
            if raw is not None:
                return _result_from_wire(raw)

    def stop_task(self, task_id, timeout=5.0, signal="SIGTERM"):
        self._call(
            "StopTask",
            {"task_id": task_id, "timeout": timeout, "signal": signal},
            timeout=timeout + 10.0,
        )

    def destroy_task(self, task_id, force=False):
        self._call(
            "DestroyTask", {"task_id": task_id, "force": force}
        )

    def signal_task(self, task_id, signal="SIGTERM"):
        self._call(
            "SignalTask", {"task_id": task_id, "signal": signal}
        )

    def exec_task(self, task_id, argv, timeout=30.0, env=None, cwd=""):
        resp = self._call(
            "ExecTask",
            {
                "task_id": task_id,
                "argv": list(argv),
                "timeout": timeout,
                "env": env or {},
                "cwd": cwd,
            },
            timeout=timeout,
        )
        return int(resp["exit_code"]), bytes(
            resp.get("output", b"") or b""
        )

    def inspect_task(self, task_id):
        raise NotImplementedError

    def recover_task(self, task_id, handle_state) -> bool:
        return bool(
            self._call(
                "RecoverTask",
                {"task_id": task_id, "handle_state": handle_state},
            )
        )

    def shutdown(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
        self.proc.terminate()
        try:
            self.proc.wait(5.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()


# ---------------------------------------------------------------------------
# plugin side
# ---------------------------------------------------------------------------


def serve_plugin(driver: DriverPlugin, socket_path: str = "") -> None:
    """Serve a DriverPlugin over the wire protocol; prints the
    handshake and blocks (reference plugins/base/plugin.go Serve)."""
    socket_path = socket_path or os.path.join(
        tempfile.mkdtemp(prefix="nomad-plugin-"), "plugin.sock"
    )
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(socket_path)
    srv.listen(4)
    print(
        f"{HANDSHAKE_CORE}|{HANDSHAKE_PROTO}|unix|{socket_path}|wire",
        flush=True,
    )

    def handle(conn):
        while True:
            frame = recv_frame(conn)
            if frame is None:
                return
            method, body = decode(frame)
            try:
                result = _dispatch(driver, method, body)
            except RecoverableError as exc:
                result = {"error": str(exc), "recoverable": True}
            except Exception as exc:  # noqa: BLE001
                result = {"error": f"{type(exc).__name__}: {exc}"}
            send_frame(conn, encode(result))

    while True:
        conn, _addr = srv.accept()
        threading.Thread(
            target=handle, args=(conn,), daemon=True
        ).start()


def _dispatch(driver: DriverPlugin, method: str, body: Dict):
    if method == "Fingerprint":
        return driver.fingerprint()
    if method == "StartTask":
        driver.start_task(_cfg_from_wire(body))
        return {}
    if method == "WaitTask":
        return _result_to_wire(
            driver.wait_task(body["task_id"], body.get("timeout"))
        )
    if method == "StopTask":
        driver.stop_task(
            body["task_id"],
            timeout=body.get("timeout", 5.0),
            signal=body.get("signal", "SIGTERM"),
        )
        return {}
    if method == "DestroyTask":
        driver.destroy_task(
            body["task_id"], force=body.get("force", False)
        )
        return {}
    if method == "SignalTask":
        driver.signal_task(
            body["task_id"], body.get("signal", "SIGTERM")
        )
        return {}
    if method == "ExecTask":
        code, output = driver.exec_task(
            body["task_id"],
            body.get("argv") or [],
            timeout=body.get("timeout", 30.0),
            env=body.get("env") or {},
            cwd=body.get("cwd", ""),
        )
        return {"exit_code": code, "output": output}
    if method == "RecoverTask":
        return driver.recover_task(
            body["task_id"], body.get("handle_state") or {}
        )
    raise ValueError(f"unknown plugin method {method!r}")


def main(argv=None) -> None:
    """``python -m nomad_tpu.client.drivers.external <builtin>`` —
    serve a builtin driver as an external plugin process."""
    from . import new_driver

    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 1:
        print(
            "usage: python -m nomad_tpu.client.drivers.external "
            "<driver-name>",
            file=sys.stderr,
        )
        sys.exit(2)
    serve_plugin(new_driver(args[0]))


if __name__ == "__main__":
    main()
