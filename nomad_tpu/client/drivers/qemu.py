"""QEMU virtual machine driver (reference drivers/qemu/driver.go).

Boots a VM image with ``qemu-system-<arch>``; memory comes from the
task's resources, vCPUs from the ``cpus`` config knob, port forwards
from ``port_map`` (reference
qemu/driver.go user-mode networking hostfwd rules).  Graceful shutdown
uses the QEMU monitor's ``system_powerdown`` when a monitor socket was
configured, else SIGTERM on the process group.
"""
from __future__ import annotations

import os
import platform
import shutil
import subprocess
from typing import Dict

from .base import TaskConfig
from .exec import RawExecDriver


def _default_binary() -> str:
    arch = platform.machine()
    mapping = {"x86_64": "qemu-system-x86_64", "aarch64": "qemu-system-aarch64"}
    return mapping.get(arch, f"qemu-system-{arch}")


class QemuDriver(RawExecDriver):
    name = "qemu"

    def __init__(self) -> None:
        super().__init__()
        self._qemu = shutil.which(_default_binary()) or shutil.which(
            "qemu-system-x86_64"
        )

    def fingerprint(self) -> Dict[str, str]:
        if not self._qemu:
            return {f"driver.{self.name}": "0"}
        attrs = {f"driver.{self.name}": "1"}
        try:
            out = subprocess.run(
                [self._qemu, "--version"],
                capture_output=True, text=True, timeout=10,
            )
            first = (out.stdout or "").splitlines()
            if first:
                # "QEMU emulator version X.Y.Z ..."
                parts = first[0].split("version")
                if len(parts) > 1:
                    attrs[f"driver.{self.name}.version"] = (
                        parts[1].strip().split()[0]
                    )
        except (OSError, subprocess.TimeoutExpired):
            pass
        return attrs

    def _build_command(self, cfg: TaskConfig):
        if not self._qemu:
            raise RuntimeError("qemu binary not found on this node")
        image = cfg.config.get("image_path", "")
        if not image:
            raise ValueError("qemu driver requires image_path in config")
        if cfg.task_dir and not os.path.isabs(image):
            image = os.path.join(cfg.task_dir, image)
        mem_mb = 512
        # vCPU count from config (the resource ask is in MHz shares,
        # not cores, so an explicit knob is the honest mapping)
        cpus = max(1, int(cfg.config.get("cpus", 1)))
        if cfg.resources is not None:
            mem_mb = max(1, int(cfg.resources.memory_mb))
        # machine type must match the emulated arch: "pc" for x86
        # (including qemu-kvm spellings), "virt" for arm/riscv boards
        binary = os.path.basename(self._qemu)
        machine = cfg.config.get(
            "machine",
            "virt"
            if any(a in binary for a in ("aarch64", "arm", "riscv"))
            else "pc",
        )
        accel = cfg.config.get("accelerator", "tcg")
        argv = [
            self._qemu,
            "-machine", f"type={machine},accel={accel}",
            "-m", f"{mem_mb}M",
            "-smp", str(cpus),
            "-drive", f"file={image},format=qcow2",
            "-nographic",
        ]
        # user-net port forwards: {"guest_port_label": host_port}
        port_map: Dict[str, int] = cfg.config.get("port_map", {}) or {}
        if port_map:
            fwds = ",".join(
                f"hostfwd=tcp::{host}-:{guest}"
                for guest, host in (
                    (int(g), int(h)) for g, h in port_map.items()
                )
            )
            argv += ["-netdev", f"user,id=user.0,{fwds}",
                     "-device", "virtio-net,netdev=user.0"]
        argv += list(cfg.config.get("args", []))
        return argv
