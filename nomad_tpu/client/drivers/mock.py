"""Mock driver: scriptable fault injection for tests
(reference drivers/mock/driver.go:75-101).

Config keys (all optional):
  run_for             seconds the task "runs" before exiting (default 0)
  exit_code           exit code when it exits
  exit_signal         signal number when it exits
  start_error         error message raised from start_task
  start_error_recoverable   whether that error is recoverable
  start_block_for     seconds start_task blocks before returning
  kill_after          seconds after a stop request before the task dies
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .base import (
    DriverHandle,
    DriverPlugin,
    RecoverableError,
    TaskConfig,
    TaskExitResult,
)


def _dur(value, default: float = 0.0) -> float:
    """Duration config values arrive as numbers from test code but as
    Go-style strings ("100ms", "10s", "1m") from HCL jobspecs — the
    reference's mock driver declares them time.Duration
    (drivers/mock/driver.go:101).  Delegates to the canonical parser."""
    from ...config import _duration_s

    return _duration_s(value, default)


class MockDriver(DriverPlugin):
    name = "mock_driver"

    def __init__(self) -> None:
        self.handles: Dict[str, DriverHandle] = {}
        self._timers: Dict[str, threading.Timer] = {}

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        conf = cfg.config
        if conf.get("start_block_for"):
            time.sleep(_dur(conf["start_block_for"]))
        if conf.get("start_error"):
            if conf.get("start_error_recoverable"):
                raise RecoverableError(conf["start_error"])
            raise RuntimeError(conf["start_error"])

        handle = DriverHandle(cfg.id)
        self.handles[cfg.id] = handle
        run_for = _dur(conf.get("run_for"), 0.0)
        exit_code = int(conf.get("exit_code", 0))
        exit_signal = int(conf.get("exit_signal", 0))

        def finish():
            handle.set_exit(
                TaskExitResult(exit_code=exit_code, signal=exit_signal)
            )

        if run_for > 0:
            timer = threading.Timer(run_for, finish)
            timer.daemon = True
            timer.start()
            self._timers[cfg.id] = timer
        elif run_for < 0:
            pass  # run forever until stopped
        else:
            finish()
        return handle

    def wait_task(self, task_id, timeout=None):
        handle = self.handles.get(task_id)
        if handle is None:
            return TaskExitResult(err="unknown task")
        return handle.wait(timeout)

    def stop_task(self, task_id, timeout=5.0, signal="SIGTERM"):
        handle = self.handles.get(task_id)
        if handle is None:
            return
        timer = self._timers.pop(task_id, None)
        if timer is not None:
            timer.cancel()
        kill_after = 0.0
        if handle.is_running():
            if kill_after > 0:
                time.sleep(kill_after)
            handle.set_exit(TaskExitResult(exit_code=0, signal=15))

    def exec_task(self, task_id, argv, timeout=30.0, env=None, cwd=""):
        if task_id not in self.handles:
            raise KeyError(f"unknown task {task_id!r}")
        return 0, ("mock exec: " + " ".join(argv)).encode()

    def signal_task(self, task_id, signal="SIGTERM"):
        # recorded so tests can assert delivery (fault injection)
        self.signals = getattr(self, "signals", [])
        self.signals.append((task_id, signal))

    def destroy_task(self, task_id, force=False):
        self.stop_task(task_id)
        self.handles.pop(task_id, None)

    def inspect_task(self, task_id):
        return self.handles.get(task_id)

    def recover_task(self, task_id, handle_state):
        # mock tasks do not survive a client restart
        return False
