"""Driver plugin contract (reference plugins/drivers/driver.go:40)."""
from __future__ import annotations

import queue
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class ExecStreamHandle:
    """A live interactive command in a task's context (reference
    ExecTaskStreaming): stdin accepts writes, stdout/stderr arrive as
    (stream, bytes) events, exit is observable.

    Pumped by two reader threads into one queue so the transport
    bridge (websocket frames, tests) consumes a single ordered
    stream; a None event means both outputs reached EOF."""

    def __init__(self, argv, env=None, cwd: str = "") -> None:
        self.proc = subprocess.Popen(
            argv,
            cwd=cwd or None,
            env=env,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        self.events: "queue.Queue" = queue.Queue()
        self._open_streams = 2

        def pump(stream, name):
            try:
                while True:
                    data = stream.read1(65536)
                    if not data:
                        break
                    self.events.put((name, data))
            except (OSError, ValueError):
                pass
            finally:
                with self._lock:
                    self._open_streams -= 1
                    if self._open_streams == 0:
                        self.events.put(None)

        self._lock = threading.Lock()
        for stream, name in (
            (self.proc.stdout, "stdout"),
            (self.proc.stderr, "stderr"),
        ):
            threading.Thread(
                target=pump, args=(stream, name), daemon=True
            ).start()

    def write_stdin(self, data: bytes) -> None:
        try:
            self.proc.stdin.write(data)
            self.proc.stdin.flush()
        except (OSError, ValueError):
            pass

    def close_stdin(self) -> None:
        try:
            self.proc.stdin.close()
        except (OSError, ValueError):
            pass

    def read_event(self, timeout: Optional[float] = None):
        """(stream, bytes), or None once both outputs hit EOF, or
        raises queue.Empty on timeout."""
        return self.events.get(timeout=timeout)

    def resize(self, height: int, width: int) -> None:
        """Terminal resize — a no-op without a pty; kept so the
        transport accepts the reference's tty_size frames."""

    def wait(self, timeout: Optional[float] = None) -> int:
        return self.proc.wait(timeout)

    def terminate(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass


@dataclass
class TaskExitResult:
    exit_code: int = 0
    signal: int = 0
    oom_killed: bool = False
    err: Optional[str] = None

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and self.err is None


@dataclass
class TaskConfig:
    """What StartTask receives: task identity + interpolated config +
    resources + env."""

    id: str = ""
    name: str = ""
    alloc_id: str = ""
    config: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    alloc_dir: str = ""
    # task working dir (TaskDir.local_dir) when the allocdir layout is
    # built; falls back to alloc_dir otherwise
    task_dir: str = ""
    # when set, drivers pump stdout/stderr through logmon rotators in
    # this directory instead of flat files (reference LogConfig,
    # structs.go; client/logmon)
    logs_dir: str = ""
    log_max_files: int = 10
    log_max_file_size_mb: int = 10
    resources: Optional[object] = None


class DriverHandle:
    """A running task instance (reference drivers' TaskHandle)."""

    def __init__(self, task_id: str) -> None:
        self.task_id = task_id
        self._exit = threading.Event()
        self._result: Optional[TaskExitResult] = None

    def set_exit(self, result: TaskExitResult) -> None:
        self._result = result
        self._exit.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[TaskExitResult]:
        if not self._exit.wait(timeout):
            return None
        return self._result

    def is_running(self) -> bool:
        return not self._exit.is_set()


class RecoverableError(Exception):
    """Start failure the task runner may retry
    (reference plugins/drivers/errors.go)."""


class DriverPlugin:
    """Lifecycle surface shared by all drivers."""

    name = "base"

    def fingerprint(self) -> Dict[str, str]:
        """Detected/healthy attributes, merged into the node."""
        return {f"driver.{self.name}": "1"}

    def start_task(self, cfg: TaskConfig) -> DriverHandle:
        raise NotImplementedError

    def wait_task(
        self, task_id: str, timeout: Optional[float] = None
    ) -> Optional[TaskExitResult]:
        raise NotImplementedError

    def stop_task(
        self, task_id: str, timeout: float = 5.0, signal: str = "SIGTERM"
    ) -> None:
        raise NotImplementedError

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        raise NotImplementedError

    def signal_task(self, task_id: str, signal: str = "SIGTERM") -> None:
        """Deliver a signal without the stop escalation
        (reference DriverPlugin.SignalTask)."""
        raise NotImplementedError

    def exec_task(
        self,
        task_id: str,
        argv,
        timeout: float = 30.0,
        env=None,
        cwd: str = "",
    ):
        """Run a command in the task's context; returns
        (exit_code, combined_output_bytes) (reference
        DriverPlugin.ExecTask backing `nomad alloc exec`)."""
        raise NotImplementedError

    def exec_task_stream(
        self,
        task_id: str,
        argv,
        env=None,
        cwd: str = "",
    ) -> "ExecStreamHandle":
        """Interactive exec in the task's context: a live handle with
        stdin writes and streamed stdout/stderr (reference
        DriverPlugin.ExecTaskStreaming backing `nomad alloc exec -i`
        over the websocket transport)."""
        if task_id not in getattr(self, "handles", {}):
            raise KeyError(f"unknown task {task_id!r}")
        return ExecStreamHandle(list(argv), env=env, cwd=cwd)

    def inspect_task(self, task_id: str) -> Optional[DriverHandle]:
        raise NotImplementedError

    def handle_state(self, task_id: str) -> Dict:
        """Driver-specific reattach metadata persisted with the task
        snapshot (e.g. docker's container id); {} when the driver has
        nothing to reattach to."""
        return {}

    def recover_task(self, task_id: str, handle_state: Dict) -> bool:
        """Reattach to a task after client restart
        (reference DriverPlugin.RecoverTask)."""
        return False
