"""Task log rotation (reference client/logmon/logmon.go +
client/lib/fifo + logging/logrotator).

The reference runs a separate ``logmon`` go-plugin process per task that
pumps the task's stdout/stderr FIFOs into size-rotated files under the
alloc's shared ``logs/`` dir.  Here the same pump-into-rotator design
runs as threads: drivers hand us pipe file objects and we stream them
into ``<logs>/<task>.{stdout,stderr}.N`` with ``LogConfig``-equivalent
max-file-size / max-files limits (structs.go LogConfig: 10 files x
10 MiB default).
"""
from __future__ import annotations

import os
import threading
from typing import BinaryIO, List, Optional, Tuple

DEFAULT_MAX_FILES = 10
DEFAULT_MAX_FILE_SIZE_MB = 10


class FileRotator:
    """Size-based rotating writer (reference logging/rotator.go).

    Files are named ``<base>.<idx>`` with monotonically increasing idx;
    once ``max_files`` exist the oldest is deleted.
    """

    def __init__(
        self,
        dir_path: str,
        base_name: str,
        max_files: int = DEFAULT_MAX_FILES,
        max_file_size_bytes: int = DEFAULT_MAX_FILE_SIZE_MB * 1024 * 1024,
    ) -> None:
        self.dir = dir_path
        self.base = base_name
        self.max_files = max(1, max_files)
        self.max_bytes = max(1, max_file_size_bytes)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._idx = self._latest_index()
        self._fh: Optional[BinaryIO] = None
        self._size = 0
        self._open_current()

    # ------------------------------------------------------------------

    def _path(self, idx: int) -> str:
        return os.path.join(self.dir, f"{self.base}.{idx}")

    def _latest_index(self) -> int:
        best = 0
        prefix = self.base + "."
        try:
            entries = os.listdir(self.dir)
        except OSError:
            return 0
        for entry in entries:
            if entry.startswith(prefix):
                try:
                    best = max(best, int(entry[len(prefix):]))
                except ValueError:
                    pass
        return best

    def _open_current(self) -> None:
        path = self._path(self._idx)
        self._fh = open(path, "ab")
        self._size = self._fh.tell()

    def _rotate(self) -> None:
        if self._fh is not None:
            self._fh.close()
        self._idx += 1
        self._open_current()
        # prune beyond max_files
        floor = self._idx - self.max_files + 1
        for idx in range(max(0, floor - 8), floor):
            try:
                os.unlink(self._path(idx))
            except OSError:
                pass

    # ------------------------------------------------------------------

    def write(self, data: bytes) -> int:
        with self._lock:
            if self._fh is None:
                self._open_current()
            remaining = data
            while remaining:
                space = self.max_bytes - self._size
                if space <= 0:
                    self._rotate()
                    space = self.max_bytes
                chunk = remaining[:space]
                self._fh.write(chunk)
                self._size += len(chunk)
                remaining = remaining[len(chunk):]
            self._fh.flush()
            return len(data)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def existing_files(self) -> List[str]:
        prefix = self.base + "."
        try:
            names = [
                n for n in os.listdir(self.dir) if n.startswith(prefix)
            ]
        except OSError:
            return []
        return sorted(
            names, key=lambda n: int(n[len(prefix):])
        )


class LogMon:
    """Per-task stdout+stderr rotators plus pipe pumps
    (reference logmon.Start: creates the two rotators and wires FIFOs)."""

    def __init__(
        self,
        log_dir: str,
        task_name: str,
        max_files: int = DEFAULT_MAX_FILES,
        max_file_size_mb: int = DEFAULT_MAX_FILE_SIZE_MB,
    ) -> None:
        size = max_file_size_mb * 1024 * 1024
        self.stdout = FileRotator(
            log_dir, f"{task_name}.stdout", max_files, size
        )
        self.stderr = FileRotator(
            log_dir, f"{task_name}.stderr", max_files, size
        )
        self._pumps: List[threading.Thread] = []

    def pump(self, stream: BinaryIO, which: str = "stdout") -> None:
        """Stream a pipe into the matching rotator until EOF."""
        rot = self.stdout if which == "stdout" else self.stderr

        # partial reads so live output lands before the task exits —
        # BufferedReader.read(n) would block for the full n bytes
        read = getattr(stream, "read1", stream.read)

        def run() -> None:
            try:
                while True:
                    chunk = read(4096)
                    if not chunk:
                        break
                    rot.write(chunk)
            except (OSError, ValueError):
                pass
            finally:
                try:
                    stream.close()
                except OSError:
                    pass

        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._pumps.append(t)

    def wait(self, timeout: Optional[float] = None) -> None:
        for t in self._pumps:
            t.join(timeout)

    def close(self) -> None:
        self.stdout.close()
        self.stderr.close()


def read_task_log(
    log_dir: str, task_name: str, kind: str = "stdout",
    max_bytes: int = 64 * 1024,
) -> bytes:
    """Tail the logical log across rotated files, newest last
    (reference client fs logs endpoint semantics)."""
    rot_prefix = f"{task_name}.{kind}."
    try:
        names = [
            n
            for n in os.listdir(log_dir)
            if n.startswith(rot_prefix)
            and n[len(rot_prefix):].isdigit()
        ]
    except OSError:
        return b""
    names.sort(key=lambda n: int(n[len(rot_prefix):]))
    out = b""
    for name in reversed(names):
        try:
            with open(os.path.join(log_dir, name), "rb") as f:
                data = f.read()
        except OSError:
            continue
        out = data + out
        if len(out) >= max_bytes:
            break
    return out[-max_bytes:]


def follow_task_log(
    log_dir: str,
    task_name: str,
    kind: str,
    cursor: Optional[Tuple[int, int]],
    flat_path: str = "",
    max_step_bytes: int = 256 * 1024,
) -> Tuple[bytes, Tuple[int, int]]:
    """One follow step: bytes appended since `cursor` and the new
    cursor, for the streaming `alloc logs -f` transport (reference
    client fs streaming frames).

    The cursor is (rotation_index, offset) into the logmon layout;
    when rotation advances, the remainder of the old file is drained
    before moving to the new one.  A client whose task predates logmon
    (flat `<task>.<kind>` files) follows `flat_path` with cursor
    (-1, offset)."""
    rot_prefix = f"{task_name}.{kind}."
    try:
        names = [
            n
            for n in os.listdir(log_dir)
            if n.startswith(rot_prefix)
            and n[len(rot_prefix):].isdigit()
        ]
    except OSError:
        # a transient listdir failure (EACCES/ENFILE/NFS blip) is NOT
        # "the rotated files vanished": converting an established
        # cursor to the flat layout here would replay retained bytes
        # once the directory reappears — hold position and retry
        if cursor is not None:
            return b"", cursor
        names = []
    if not names:
        # flat legacy layout.  A follower holding an established
        # ROTATION cursor that lands here means the rotated files
        # vanished mid-follow (task GC / restart) — restarting the
        # flat file at offset 0 would replay bytes the consumer
        # already saw, so resume at its current end instead.
        if cursor and cursor[0] == -1:
            offset = cursor[1]
        elif cursor and cursor[0] >= 0:
            # only migrate to the flat layout when a flat file actually
            # exists; in the transient window where BOTH are gone, hold
            # the rotation cursor unchanged — degrading to (-1, 0) here
            # would replay a later-recreated rotation file from scratch
            try:
                offset = os.path.getsize(flat_path) if flat_path else None
            except OSError:
                offset = None
            if offset is None:
                return b"", cursor
            return b"", (-1, offset)
        else:
            offset = 0
        if not flat_path:
            return b"", (-1, offset)
        try:
            with open(flat_path, "rb") as f:
                f.seek(offset)
                data = f.read(max_step_bytes)
        except OSError:
            return b"", (-1, offset)
        return data, (-1, offset + len(data))

    indexes = sorted(int(n[len(rot_prefix):]) for n in names)
    if (
        cursor is not None
        and cursor[0] >= 0
        and cursor[0] not in indexes
        and indexes[-1] < cursor[0]
    ):
        # the retained indexes RESTARTED below an established cursor
        # (restart recreated index 0 after GC): the follower can't
        # distinguish a recreated index from one it already streamed,
        # so replaying from the oldest retained file risks duplicate
        # bytes — resume at the newest file's end and follow forward
        path = os.path.join(log_dir, f"{rot_prefix}{indexes[-1]}")
        try:
            end = os.path.getsize(path)
        except OSError:
            end = 0
        return b"", (indexes[-1], end)
    if cursor is None or cursor[0] == -1 or cursor[0] not in indexes:
        # start at the beginning of the oldest retained file; for an
        # established cursor whose file was pruned (indexes advanced
        # PAST it) this is still duplicate-free — retention only drops
        # OLD files, so every retained index is strictly newer than
        # anything already read
        cursor = (indexes[0], 0)
    idx, offset = cursor
    out = b""
    new_cursor = cursor
    # bounded per step: a fresh follower attaching to a task with a
    # full rotation window must not slurp the whole retained history
    # into one buffer — the cursor resumes where this step stopped
    budget = max_step_bytes
    for i in indexes:
        if i < idx:
            continue
        start = offset if i == idx else 0
        path = os.path.join(log_dir, f"{rot_prefix}{i}")
        try:
            with open(path, "rb") as f:
                f.seek(start)
                data = f.read(budget)
        except OSError:
            data = b""
        out += data
        budget -= len(data)
        new_cursor = (i, start + len(data))
        if budget <= 0:
            break
    return out, new_cursor
