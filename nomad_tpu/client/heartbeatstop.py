"""Stop allocs after losing contact with servers
(reference client/heartbeatstop.go:43-60).

Task groups can set ``stop_after_client_disconnect``; when the client's
last successful heartbeat is older than an alloc's configured timeout,
the alloc is stopped locally even though no server told us to — the
servers will independently mark it lost and reschedule it, and this
prevents a split-brain double-run when the partition heals.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class HeartbeatStopper:
    def __init__(
        self,
        stop_alloc_fn: Callable[[str], None],
        check_interval: float = 1.0,
        min_grace: float = 0.0,
    ) -> None:
        self.stop_alloc_fn = stop_alloc_fn
        self.check_interval = check_interval
        # floor on the effective timeout: an alloc must never be
        # stopped between two healthy heartbeats (reference
        # heartbeatstop.go watches the server-assigned TTL; callers
        # pass ~2x their heartbeat interval)
        self.min_grace = min_grace
        self._lock = threading.Lock()
        # alloc_id -> stop_after seconds
        self._watched: Dict[str, float] = {}
        self._last_ok = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------

    def allocation_hook(self, alloc) -> None:
        """Track an alloc if its group opts in
        (reference heartbeatstop.go allocHook)."""
        if alloc.should_client_stop():
            tg = alloc.job.lookup_task_group(alloc.task_group)
            with self._lock:
                self._watched[alloc.id] = float(
                    tg.stop_after_client_disconnect_s or 0.0
                )

    def remove(self, alloc_id: str) -> None:
        with self._lock:
            self._watched.pop(alloc_id, None)

    def note_heartbeat_ok(self) -> None:
        with self._lock:
            self._last_ok = time.time()

    # ------------------------------------------------------------------

    def expired(self) -> Dict[str, float]:
        """Allocs whose stop_after has elapsed since the last good
        heartbeat."""
        now = time.time()
        with self._lock:
            since = now - self._last_ok
            return {
                alloc_id: timeout
                for alloc_id, timeout in self._watched.items()
                if since > max(timeout, self.min_grace)
            }

    def check_once(self) -> int:
        stopped = 0
        for alloc_id in list(self.expired()):
            self.remove(alloc_id)
            try:
                self.stop_alloc_fn(alloc_id)
                stopped += 1
            except Exception:  # noqa: BLE001
                pass
        return stopped

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="heartbeat-stop", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval):
            self.check_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
