"""Allocation directory layout (reference client/allocdir/alloc_dir.go,
task_dir.go).

Layout under the client data dir::

    allocs/<alloc_id>/
        alloc/              shared dir, all tasks of the group
            data/           persisted across in-place restarts, migrated
                            when EphemeralDisk.migrate is set
            logs/           rotated task stdout/stderr (logmon target)
            tmp/
        <task>/
            local/          task-private scratch (NOMAD_TASK_DIR)
            secrets/        rendered secrets (NOMAD_SECRETS_DIR)
            tmp/

The reference chroots/binds these on Linux (alloc_dir_linux.go); here the
layout + lifecycle + migration semantics are kept and isolation is the
driver's concern.
"""
from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional

SHARED_ALLOC_NAME = "alloc"
SHARED_DATA_DIR = "data"
SHARED_LOGS_DIR = "logs"
TASK_LOCAL = "local"
TASK_SECRETS = "secrets"
TMP_DIR = "tmp"


class TaskDir:
    """Per-task view of an alloc dir (reference allocdir/task_dir.go)."""

    def __init__(self, alloc_path: str, task_name: str) -> None:
        self.task_name = task_name
        self.dir = os.path.join(alloc_path, task_name)
        self.local_dir = os.path.join(self.dir, TASK_LOCAL)
        self.secrets_dir = os.path.join(self.dir, TASK_SECRETS)
        self.tmp_dir = os.path.join(self.dir, TMP_DIR)
        self.shared_alloc_dir = os.path.join(alloc_path, SHARED_ALLOC_NAME)
        self.log_dir = os.path.join(self.shared_alloc_dir, SHARED_LOGS_DIR)

    def build(self) -> None:
        for d in (self.local_dir, self.secrets_dir, self.tmp_dir):
            os.makedirs(d, exist_ok=True)


class AllocDir:
    """One allocation's directory tree (reference allocdir/alloc_dir.go:
    Build, Destroy, Move, Snapshot)."""

    def __init__(self, base_dir: str, alloc_id: str) -> None:
        self.alloc_id = alloc_id
        self.alloc_dir = os.path.join(base_dir, alloc_id)
        self.shared_dir = os.path.join(self.alloc_dir, SHARED_ALLOC_NAME)
        self.data_dir = os.path.join(self.shared_dir, SHARED_DATA_DIR)
        self.log_dir = os.path.join(self.shared_dir, SHARED_LOGS_DIR)
        self.task_dirs: Dict[str, TaskDir] = {}
        self.built = False

    def new_task_dir(self, task_name: str) -> TaskDir:
        td = TaskDir(self.alloc_dir, task_name)
        self.task_dirs[task_name] = td
        return td

    def build(self) -> None:
        for d in (
            self.data_dir,
            self.log_dir,
            os.path.join(self.shared_dir, TMP_DIR),
        ):
            os.makedirs(d, exist_ok=True)
        for td in self.task_dirs.values():
            td.build()
        self.built = True

    def destroy(self) -> None:
        shutil.rmtree(self.alloc_dir, ignore_errors=True)
        self.built = False

    # -- migration (reference alloc_dir.go Move, used by the
    # previous-alloc watcher for sticky ephemeral disks) ---------------

    def move_from(self, other: "AllocDir") -> None:
        """Move the sticky pieces of a previous allocation's dir into
        this one: the shared data dir and each task's local dir."""
        self.build()
        _move_contents(other.data_dir, self.data_dir)
        for name, td in self.task_dirs.items():
            prev = other.task_dirs.get(name) or TaskDir(
                other.alloc_dir, name
            )
            if os.path.isdir(prev.local_dir):
                _move_contents(prev.local_dir, td.local_dir)

    # -- accounting (reference client/gc.go + allocdir stats) ----------

    def disk_usage_bytes(self) -> int:
        total = 0
        for root, _dirs, files in os.walk(self.alloc_dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return total

    def list_files(self, rel: str = "") -> List[str]:
        """Relative listing for the fs API (reference client fs
        endpoint)."""
        base = os.path.join(self.alloc_dir, rel) if rel else self.alloc_dir
        out: List[str] = []
        for root, _dirs, files in os.walk(base):
            for f in files:
                out.append(
                    os.path.relpath(os.path.join(root, f), self.alloc_dir)
                )
        return sorted(out)


def _move_contents(src: str, dst: str) -> None:
    if not os.path.isdir(src):
        return
    os.makedirs(dst, exist_ok=True)
    for entry in os.listdir(src):
        s = os.path.join(src, entry)
        d = os.path.join(dst, entry)
        try:
            shutil.move(s, d)
        except (OSError, shutil.Error):
            pass


def find_alloc_dir(base_dir: str, alloc_id: str) -> Optional[AllocDir]:
    """Reopen an existing alloc dir (client restart / migration)."""
    path = os.path.join(base_dir, alloc_id)
    if not os.path.isdir(path):
        return None
    ad = AllocDir(base_dir, alloc_id)
    for entry in os.listdir(path):
        if entry == SHARED_ALLOC_NAME:
            continue
        if os.path.isdir(os.path.join(path, entry)):
            ad.new_task_dir(entry)
    ad.built = True
    return ad
