"""Device plugin framework + manager (reference plugins/device/device.go
DevicePlugin: Fingerprint stream, Reserve, Stats; client/devicemanager/
manager.go; devices/gpu/nvidia as the canonical plugin).

TPU-native: the flagship plugin fingerprints attached TPU/accelerator
chips through JAX (the nvml analog, devices/gpu/nvidia/device.go:88) and
its Reserve hands back the env pinning a task to its reserved chips
(``JAX_VISIBLE_DEVICES``/``TPU_VISIBLE_CHIPS``) the way the nvidia
plugin returns ``CUDA_VISIBLE_DEVICES``.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs import Node, NodeDeviceResource


@dataclass
class ReservationSpec:
    """What Reserve returns (reference device.proto ContainerReservation:
    env + mounts + devices)."""

    envs: Dict[str, str] = field(default_factory=dict)
    mounts: List[Dict[str, str]] = field(default_factory=list)
    devices: List[Dict[str, str]] = field(default_factory=list)


class DevicePlugin:
    """Plugin surface (reference plugins/device/device.go:DevicePlugin).
    """

    vendor = ""
    type = ""

    def fingerprint(self) -> List[NodeDeviceResource]:
        """Detected device groups + attributes."""
        raise NotImplementedError

    def reserve(self, device_ids: List[str]) -> ReservationSpec:
        """Claim instances for a task; returns env/mount specs."""
        raise NotImplementedError

    def stats(self) -> Dict[str, Dict[str, float]]:
        """instance id -> stats map (reference Stats stream)."""
        return {}


class TPUDevicePlugin(DevicePlugin):
    """Accelerator plugin backed by JAX (devices/gpu/nvidia analog)."""

    vendor = "google"
    type = "tpu"

    def __init__(self) -> None:
        self._devices = None

    def _detect(self):
        if self._devices is None:
            from .fingerprint import bounded_jax_devices

            devices = bounded_jax_devices()
            self._devices = [
                d for d in (devices or []) if d.platform != "cpu"
            ]
        return self._devices

    def fingerprint(self) -> List[NodeDeviceResource]:
        devices = self._detect()
        by_kind: Dict[str, List] = {}
        for d in devices:
            by_kind.setdefault(d.device_kind, []).append(d)
        out = []
        for kind, devs in by_kind.items():
            out.append(
                NodeDeviceResource(
                    vendor=self.vendor,
                    type=self.type,
                    name=kind.replace(" ", "-").lower(),
                    instance_ids=[str(d.id) for d in devs],
                    attributes={
                        "platform": devs[0].platform,
                        "count": str(len(devs)),
                    },
                )
            )
        return out

    def reserve(self, device_ids: List[str]) -> ReservationSpec:
        ids = ",".join(device_ids)
        return ReservationSpec(
            envs={
                "JAX_VISIBLE_DEVICES": ids,
                "TPU_VISIBLE_CHIPS": ids,
            }
        )

    def stats(self) -> Dict[str, Dict[str, float]]:
        devices = self._detect()
        out: Dict[str, Dict[str, float]] = {}
        for d in devices:
            stats: Dict[str, float] = {}
            try:
                mem = d.memory_stats()
                stats["bytes_in_use"] = float(mem.get("bytes_in_use", 0))
                stats["bytes_limit"] = float(
                    mem.get("bytes_limit", 0)
                )
            except Exception:  # noqa: BLE001
                pass
            out[str(d.id)] = stats
        return out


class DeviceManager:
    """Client-side device plugin lifecycle + reservation bookkeeping
    (reference client/devicemanager/manager.go + the instance tracking
    the task runner's device hook relies on)."""

    def __init__(self, plugins: Optional[List[DevicePlugin]] = None):
        self.plugins: List[DevicePlugin] = (
            plugins if plugins is not None else [TPUDevicePlugin()]
        )
        self._lock = threading.Lock()
        # (vendor, type, name) -> plugin
        self._routes: Dict = {}
        # alloc_id -> list[(plugin, ids)]
        self._reservations: Dict[str, List] = {}

    def fingerprint_node(self, node: Node) -> None:
        """Fold every plugin's device groups into the node
        (reference devicemanager fingerprint fan-in)."""
        with self._lock:
            for plugin in self.plugins:
                try:
                    groups = plugin.fingerprint()
                except Exception:  # noqa: BLE001
                    continue
                for g in groups:
                    self._routes[(g.vendor, g.type, g.name)] = plugin
                    existing = [
                        d
                        for d in node.node_resources.devices
                        if d.id() == g.id()
                    ]
                    if existing:
                        existing[0].instance_ids = g.instance_ids
                        existing[0].attributes.update(g.attributes)
                    else:
                        node.node_resources.devices.append(g)

    def reserve(
        self,
        alloc_id: str,
        vendor: str,
        dev_type: str,
        name: str,
        device_ids: List[str],
    ) -> ReservationSpec:
        with self._lock:
            plugin = self._routes.get((vendor, dev_type, name))
        if plugin is None:
            raise KeyError(
                f"no device plugin for {vendor}/{dev_type}/{name}"
            )
        spec = plugin.reserve(device_ids)
        with self._lock:
            self._reservations.setdefault(alloc_id, []).append(
                (plugin, list(device_ids))
            )
        return spec

    def free(self, alloc_id: str) -> None:
        with self._lock:
            self._reservations.pop(alloc_id, None)

    def reserved_ids(self, alloc_id: str) -> List[str]:
        with self._lock:
            out: List[str] = []
            for _plugin, ids in self._reservations.get(alloc_id, []):
                out.extend(ids)
            return out

    def all_stats(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        out = {}
        for plugin in self.plugins:
            key = f"{plugin.vendor}/{plugin.type}"
            try:
                out[key] = plugin.stats()
            except Exception:  # noqa: BLE001
                out[key] = {}
        return out
