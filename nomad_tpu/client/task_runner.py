"""Task runner: drive one task through start/wait/restart
(reference client/allocrunner/taskrunner/task_runner.go:62, restart
policy logic in taskrunner/restarts/).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..structs import (
    RestartPolicy,
    Task,
    TaskState,
)
from .drivers import DriverPlugin, new_driver
from .drivers.base import RecoverableError, TaskConfig, TaskExitResult

TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"


class RestartTracker:
    """(reference client/allocrunner/taskrunner/restarts/restarts.go)"""

    def __init__(self, policy: RestartPolicy, batch: bool) -> None:
        self.policy = policy
        self.batch = batch
        self.count = 0
        self.start_time = time.time()

    def next_restart(self, result: TaskExitResult) -> Optional[float]:
        """Returns the delay before restarting, or None to stop."""
        now = time.time()
        if now - self.start_time > self.policy.interval_s:
            self.count = 0
            self.start_time = now
        # successful batch tasks never restart; services restart on any
        # exit per their policy
        if self.batch and result.successful():
            return None
        self.count += 1
        if self.count > self.policy.attempts:
            if self.policy.mode == "delay":
                self.count = 0
                self.start_time = now + self.policy.interval_s
                return self.policy.interval_s
            return None
        return self.policy.delay_s


class TaskRunner:
    def __init__(
        self,
        alloc_id: str,
        task: Task,
        restart_policy: RestartPolicy,
        batch: bool,
        alloc_dir: str = "",
        env: Optional[Dict[str, str]] = None,
        on_state_change: Optional[Callable[[str, TaskState], None]] = None,
        driver: Optional[DriverPlugin] = None,
        secrets=None,
        catalog=None,
        task_dir=None,
        task_env=None,
        payload: bytes = b"",
        extra_env: Optional[Dict[str, str]] = None,
    ) -> None:
        self.secrets = secrets
        self.catalog = catalog
        self.alloc_id = alloc_id
        self.task = task
        self.alloc_dir = alloc_dir
        # allocdir layout (client/allocdir) + resolved env
        # (client/taskenv); optional — tests drive runners bare
        self.task_dir = task_dir
        self.task_env = task_env
        # dispatch payload blob (structs.go DispatchPayloadConfig) +
        # env injected by device reservations (devices.py)
        self.payload = payload
        self.extra_env = extra_env or {}
        self.env = env or {}
        self.driver = driver or new_driver(task.driver)
        self.restarts = RestartTracker(restart_policy, batch)
        self.state = TaskState(state=TASK_STATE_PENDING)
        self.on_state_change = on_state_change
        self.task_id = f"{alloc_id[:8]}-{task.name}"
        self._kill = threading.Event()
        # user-initiated restart in flight: the next task exit loops
        # straight back to start without charging the restart policy
        # (reference taskrunner Restart() vs. restart tracker)
        self._user_restart = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.exit_result: Optional[TaskExitResult] = None

    # ------------------------------------------------------------------

    def _set_state(self, state: str, failed: bool = False, event: str = ""):
        self.state.state = state
        self.state.failed = self.state.failed or failed
        if state == TASK_STATE_RUNNING and not self.state.started_at:
            self.state.started_at = time.time()
        if state == TASK_STATE_DEAD:
            self.state.finished_at = time.time()
        if event:
            self.state.events.append(
                {"type": event, "time": time.time()}
            )
        if self.on_state_change is not None:
            self.on_state_change(self.task.name, self.state)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"task-{self.task_id}", daemon=True
        )
        self._thread.start()

    def run(self) -> None:
        """Start/wait/restart loop (reference task_runner.go:446 Run)."""
        try:
            # pre-start hooks, in the reference's taskrunner hook order:
            # dispatch_payload -> artifacts -> template
            if not self._prestart_hooks():
                return
            # render template blocks into the alloc dir before the first
            # start (reference taskrunner/template hook)
            if self.task.templates and self.alloc_dir:
                from .templates import render_task_templates

                try:
                    render_task_templates(
                        self.task.templates,
                        self.alloc_dir,
                        env={**self.env, **self.task.env},
                        meta=self.task.meta,
                        secrets=self.secrets,
                        catalog=self.catalog,
                    )
                except Exception as exc:  # noqa: BLE001
                    self.exit_result = TaskExitResult(
                        exit_code=-1, err=str(exc)
                    )
                    self._set_state(
                        TASK_STATE_DEAD, failed=True,
                        event="Template Failed",
                    )
                    return
            while not self._kill.is_set():
                config = dict(self.task.config)
                env = {**self.env, **self.task.env, **self.extra_env}
                if self.task_env is not None:
                    # ${...} interpolation over driver config
                    # (reference taskenv ParseAndReplace on the config);
                    # builder values win over the legacy flat env —
                    # they carry the allocdir-layout paths
                    config = self.task_env.replace_all(config)
                    env = {**env, **self.task_env.all()}
                # connect sidecar: resolve upstream targets from the
                # service catalog at launch (reference: upstreams are
                # rendered into the Envoy bootstrap at sidecar start)
                if config.get("connect_upstreams") is not None:
                    # the in-tree proxy runs `python -m
                    # nomad_tpu.client.connect` from the task dir:
                    # use THIS client's interpreter + package (the
                    # server that injected the task may live on a
                    # different host/venv in networked clusters)
                    import os as _os
                    import sys as _sys

                    import nomad_tpu as _pkg

                    config["command"] = _sys.executable
                    _root = _os.path.dirname(
                        _os.path.dirname(_pkg.__file__)
                    )
                    _prev = env.get(
                        "PYTHONPATH",
                        _os.environ.get("PYTHONPATH", ""),
                    )
                    env["PYTHONPATH"] = (
                        f"{_root}{_os.pathsep}{_prev}"
                        if _prev
                        else _root
                    )
                    # sidecar proxies never need an accelerator: keep
                    # them off the exclusive single-chip session (a
                    # leftover helper holding it wedges the tunnel)
                    from ..device_lock import scrub_accelerator_env

                    env = scrub_accelerator_env(env)
                for item in config.get("connect_upstreams") or []:
                    dest, _port = item[0], item[1]
                    # brief launch-time wait: the upstream's alloc is
                    # usually seconds behind; blocking here beats
                    # bouncing the proxy through restart backoff
                    deadline = time.time() + 10.0
                    target = self._resolve_upstream(dest)
                    while not target and time.time() < deadline:
                        if self._kill.wait(0.25):
                            break
                        target = self._resolve_upstream(dest)
                    if target:
                        from .connect import env_key

                        env[
                            f"NOMAD_CONNECT_TARGET_{env_key(dest)}"
                        ] = target
                cfg = TaskConfig(
                    id=self.task_id,
                    name=self.task.name,
                    alloc_id=self.alloc_id,
                    config=config,
                    env=env,
                    alloc_dir=self.alloc_dir,
                    task_dir=(
                        self.task_dir.local_dir if self.task_dir else ""
                    ),
                    logs_dir=(
                        self.task_dir.log_dir if self.task_dir else ""
                    ),
                    log_max_files=self.task.log_max_files,
                    log_max_file_size_mb=self.task.log_max_file_size_mb,
                    resources=self.task.resources,
                )
                try:
                    handle = self.driver.start_task(cfg)
                except RecoverableError as exc:
                    result = TaskExitResult(exit_code=-1, err=str(exc))
                    self._set_state(
                        TASK_STATE_PENDING, event="Driver Failure"
                    )
                    if not self._maybe_restart(result):
                        return
                    continue
                except Exception as exc:  # noqa: BLE001
                    self.exit_result = TaskExitResult(
                        exit_code=-1, err=str(exc)
                    )
                    self._set_state(
                        TASK_STATE_DEAD, failed=True,
                        event="Driver Failure",
                    )
                    return

                self._set_state(TASK_STATE_RUNNING, event="Started")

                # wait for exit or kill
                result = None
                while result is None and not self._kill.is_set():
                    result = self.driver.wait_task(self.task_id, timeout=0.1)
                if self._kill.is_set():
                    self.driver.stop_task(
                        self.task_id, timeout=self.task.kill_timeout_s
                    )
                    result = self.driver.wait_task(self.task_id, 1.0)
                    self.exit_result = result
                    self._set_state(TASK_STATE_DEAD, event="Killed")
                    return

                self.exit_result = result
                if self._user_restart.is_set():
                    self._user_restart.clear()
                    self._set_state(
                        TASK_STATE_PENDING, event="Restart Signaled"
                    )
                    continue
                if not self._maybe_restart(result):
                    return
        finally:
            # terminal teardown: release driver-side task resources
            # (reference taskrunner DestroyTask in the cleanup hooks);
            # executor-backed drivers shut their per-task executor here
            try:
                self.driver.destroy_task(self.task_id, force=True)
            except Exception:  # noqa: BLE001
                pass
            self._done.set()

    def _resolve_upstream(self, dest: str) -> str:
        """First healthy instance of a service, as host:port (reference
        resolves upstreams through Consul's catalog)."""
        if self.catalog is None:
            return ""
        try:
            instances = self.catalog.instances(dest, healthy_only=True)
        except Exception:  # noqa: BLE001
            return ""
        for inst in instances:
            if inst.port:
                return f"{inst.address or '127.0.0.1'}:{inst.port}"
        return ""

    def _prestart_hooks(self) -> bool:
        """Dispatch-payload + artifact hooks (reference
        taskrunner/dispatch_hook.go, artifact_hook.go).  Returns False
        when setup failed and the task must not start."""
        base = (
            self.task_dir.local_dir
            if self.task_dir is not None
            else self.alloc_dir
        )
        if self.payload and self.task.dispatch_payload_file and base:
            import os

            from .getter import contained_path

            try:
                path = contained_path(
                    base, self.task.dispatch_payload_file
                )
            except ValueError:
                self.exit_result = TaskExitResult(
                    exit_code=-1,
                    err="dispatch_payload_file escapes the task dir",
                )
                self._set_state(
                    TASK_STATE_DEAD, failed=True,
                    event="Failed Payload Write",
                )
                return False
            os.makedirs(os.path.dirname(path) or base, exist_ok=True)
            with open(path, "wb") as f:
                f.write(self.payload)
        if self.task.artifacts and base:
            from .getter import ArtifactError, fetch_all

            artifacts = self.task.artifacts
            if self.task_env is not None:
                artifacts = self.task_env.replace_all(artifacts)
            try:
                fetch_all(artifacts, base)
            except ArtifactError as exc:
                self.exit_result = TaskExitResult(
                    exit_code=-1, err=str(exc)
                )
                self._set_state(
                    TASK_STATE_DEAD, failed=True,
                    event="Failed Artifact Download",
                )
                return False
        return True

    def _maybe_restart(self, result: TaskExitResult) -> bool:
        delay = self.restarts.next_restart(result)
        if delay is None:
            self._set_state(
                TASK_STATE_DEAD,
                failed=not result.successful(),
                event="Terminated",
            )
            return False
        self._set_state(
            TASK_STATE_PENDING, event="Restarting"
        )
        # interruptible sleep
        if self._kill.wait(delay):
            self._set_state(TASK_STATE_DEAD, event="Killed")
            return False
        return True

    # ------------------------------------------------------------------

    def restart(self) -> None:
        """User-initiated in-place restart: stop the process; the run
        loop relaunches it without consuming restart-policy attempts."""
        if not self.is_running():
            return
        self._user_restart.set()
        self.driver.stop_task(
            self.task_id, timeout=self.task.kill_timeout_s
        )

    def kill(self) -> None:
        self._kill.set()
        # a runner killed before start() would otherwise never signal
        # done and wedge anything waiting on it
        if self._thread is None:
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def is_running(self) -> bool:
        return self.state.state == TASK_STATE_RUNNING
