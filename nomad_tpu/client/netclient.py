"""Standalone client agent process: runs the full client runtime
against a networked cluster over HTTP (the client half of the
reference's `nomad agent -client -servers=...`).

    python -m nomad_tpu.client.netclient \
        --servers http://127.0.0.1:4646[,http://...] \
        [--name NAME] [--data-dir DIR] [--drivers mock_driver,exec]

Prints ``READY <node-id> <callback-port>`` once registered, then runs
until SIGTERM/SIGINT.  Registration/heartbeats/alloc sync go to the
servers (followers forward writes to the leader); the servers reach
back through this process's callback endpoint for fs/exec/logs
(client/remote.py)."""
from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="nomad-tpu-client")
    p.add_argument(
        "--servers", required=True,
        help="comma-separated server HTTP addresses",
    )
    p.add_argument("--name", default="")
    p.add_argument("--data-dir", default="", dest="data_dir")
    p.add_argument(
        "--drivers", default="mock_driver",
        help="comma-separated builtin driver names",
    )
    p.add_argument(
        "--heartbeat-interval", type=float, default=3.0,
        dest="heartbeat_interval",
    )
    p.add_argument(
        "--watch-interval", type=float, default=0.5,
        dest="watch_interval",
        help="alloc-watch poll period; remote polls ride HTTP, so "
        "the in-process default (50ms) would hammer the servers",
    )
    p.add_argument(
        "--callback-host", default="127.0.0.1",
        dest="callback_host",
    )
    args = p.parse_args(argv)

    from ..structs import Node
    from .client import Client
    from .fingerprint import run_fingerprinters
    from .remote import RemoteServer

    node = Node()
    if args.name:
        node.name = args.name
    run_fingerprinters(node, include_tpu=False)

    remote = RemoteServer(
        args.servers.split(","), callback_host=args.callback_host
    )
    client = Client(
        remote,
        node=node,
        data_dir=args.data_dir,
        heartbeat_interval=args.heartbeat_interval,
        watch_interval=args.watch_interval,
        drivers=[d for d in args.drivers.split(",") if d],
        fingerprint=False,
    )
    client.start()
    port = remote._endpoint.port if remote._endpoint else 0
    print(f"READY {node.id} {port}", flush=True)

    stop = threading.Event()

    def _sig(*_a):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    stop.wait()
    client.stop()
    remote.stop()
    sys.exit(0)


if __name__ == "__main__":
    main()
