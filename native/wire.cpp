/* Implementation of the nomad-tpu wire codec + TCP bridge (see wire.h). */
#include "wire.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

namespace {

/* ----------------------------------------------------------------------
 * JSON value model
 * -------------------------------------------------------------------- */

struct JValue;
using JArray = std::vector<JValue>;
using JPair = std::pair<std::string, JValue>;
using JObject = std::vector<JPair>;

struct JValue {
  enum Kind { NUL, BOOL, INT, FLOAT, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;
  JArray arr;
  JObject obj;
};

/* ----------------------------------------------------------------------
 * JSON parsing (recursive descent)
 * -------------------------------------------------------------------- */

struct JParser {
  const char *p;
  const char *end;
  bool ok = true;

  explicit JParser(const char *text)
      : p(text), end(text + strlen(text)) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool literal(const char *lit) {
    size_t n = strlen(lit);
    if ((size_t)(end - p) >= n && strncmp(p, lit, n) == 0) {
      p += n;
      return true;
    }
    return false;
  }

  JValue parse_value() {
    skip_ws();
    JValue v;
    if (p >= end) {
      ok = false;
      return v;
    }
    switch (*p) {
      case 'n':
        ok = literal("null");
        return v;
      case 't':
        ok = literal("true");
        v.kind = JValue::BOOL;
        v.b = true;
        return v;
      case 'f':
        ok = literal("false");
        v.kind = JValue::BOOL;
        v.b = false;
        return v;
      case '"':
        v.kind = JValue::STR;
        v.s = parse_string();
        return v;
      case '[': {
        ++p;
        v.kind = JValue::ARR;
        skip_ws();
        if (p < end && *p == ']') {
          ++p;
          return v;
        }
        while (ok) {
          v.arr.push_back(parse_value());
          skip_ws();
          if (p < end && *p == ',') {
            ++p;
            continue;
          }
          if (p < end && *p == ']') {
            ++p;
            break;
          }
          ok = false;
        }
        return v;
      }
      case '{': {
        ++p;
        v.kind = JValue::OBJ;
        skip_ws();
        if (p < end && *p == '}') {
          ++p;
          return v;
        }
        while (ok) {
          skip_ws();
          if (p >= end || *p != '"') {
            ok = false;
            break;
          }
          std::string key = parse_string();
          skip_ws();
          if (p >= end || *p != ':') {
            ok = false;
            break;
          }
          ++p;
          v.obj.emplace_back(std::move(key), parse_value());
          skip_ws();
          if (p < end && *p == ',') {
            ++p;
            continue;
          }
          if (p < end && *p == '}') {
            ++p;
            break;
          }
          ok = false;
        }
        return v;
      }
      default:
        return parse_number();
    }
  }

  std::string parse_string() {
    std::string out;
    ++p; /* opening quote */
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out.push_back('\n'); break;
          case 't': out.push_back('\t'); break;
          case 'r': out.push_back('\r'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case '/': out.push_back('/'); break;
          case '\\': out.push_back('\\'); break;
          case '"': out.push_back('"'); break;
          case 'u': {
            if (p + 4 < end) {
              unsigned code = 0;
              sscanf(p + 1, "%4x", &code);
              p += 4;
              /* UTF-8 encode the BMP code point */
              if (code < 0x80) {
                out.push_back((char)code);
              } else if (code < 0x800) {
                out.push_back((char)(0xC0 | (code >> 6)));
                out.push_back((char)(0x80 | (code & 0x3F)));
              } else {
                out.push_back((char)(0xE0 | (code >> 12)));
                out.push_back((char)(0x80 | ((code >> 6) & 0x3F)));
                out.push_back((char)(0x80 | (code & 0x3F)));
              }
            }
            break;
          }
          default: out.push_back(*p);
        }
        ++p;
      } else {
        out.push_back(*p++);
      }
    }
    if (p < end) ++p; /* closing quote */
    return out;
  }

  JValue parse_number() {
    JValue v;
    const char *start = p;
    bool is_float = false;
    if (p < end && (*p == '-' || *p == '+')) ++p;
    while (p < end &&
           ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
            *p == 'E' || *p == '-' || *p == '+')) {
      if (*p == '.' || *p == 'e' || *p == 'E') is_float = true;
      ++p;
    }
    if (p == start) {
      ok = false;
      return v;
    }
    std::string num(start, p - start);
    if (is_float) {
      v.kind = JValue::FLOAT;
      v.f = atof(num.c_str());
    } else {
      v.kind = JValue::INT;
      v.i = strtoll(num.c_str(), nullptr, 10);
    }
    return v;
  }
};

/* ----------------------------------------------------------------------
 * JSON serialization
 * -------------------------------------------------------------------- */

void json_escape(const std::string &s, std::string &out) {
  out.push_back('"');
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out.push_back(c);
        }
    }
  }
  out.push_back('"');
}

void to_json(const JValue &v, std::string &out) {
  switch (v.kind) {
    case JValue::NUL: out += "null"; break;
    case JValue::BOOL: out += v.b ? "true" : "false"; break;
    case JValue::INT: {
      char buf[32];
      snprintf(buf, sizeof buf, "%lld", (long long)v.i);
      out += buf;
      break;
    }
    case JValue::FLOAT: {
      char buf[64];
      if (std::isfinite(v.f)) {
        snprintf(buf, sizeof buf, "%.17g", v.f);
      } else {
        snprintf(buf, sizeof buf, "null");
      }
      out += buf;
      break;
    }
    case JValue::STR: json_escape(v.s, out); break;
    case JValue::ARR: {
      out.push_back('[');
      for (size_t i = 0; i < v.arr.size(); ++i) {
        if (i) out.push_back(',');
        to_json(v.arr[i], out);
      }
      out.push_back(']');
      break;
    }
    case JValue::OBJ: {
      out.push_back('{');
      for (size_t i = 0; i < v.obj.size(); ++i) {
        if (i) out.push_back(',');
        json_escape(v.obj[i].first, out);
        out.push_back(':');
        to_json(v.obj[i].second, out);
      }
      out.push_back('}');
      break;
    }
  }
}

/* ----------------------------------------------------------------------
 * Wire encoding (msgpack-compatible wide forms)
 * -------------------------------------------------------------------- */

void put_u32(std::vector<uint8_t> &out, uint32_t v) {
  out.push_back((v >> 24) & 0xFF);
  out.push_back((v >> 16) & 0xFF);
  out.push_back((v >> 8) & 0xFF);
  out.push_back(v & 0xFF);
}

void put_u64(std::vector<uint8_t> &out, uint64_t v) {
  for (int shift = 56; shift >= 0; shift -= 8)
    out.push_back((v >> shift) & 0xFF);
}

void encode_value(const JValue &v, std::vector<uint8_t> &out) {
  switch (v.kind) {
    case JValue::NUL: out.push_back(0xc0); break;
    case JValue::BOOL: out.push_back(v.b ? 0xc3 : 0xc2); break;
    case JValue::INT:
      out.push_back(0xd3);
      put_u64(out, (uint64_t)v.i);
      break;
    case JValue::FLOAT: {
      out.push_back(0xcb);
      uint64_t bits;
      memcpy(&bits, &v.f, sizeof bits);
      put_u64(out, bits);
      break;
    }
    case JValue::STR:
      out.push_back(0xdb);
      put_u32(out, (uint32_t)v.s.size());
      out.insert(out.end(), v.s.begin(), v.s.end());
      break;
    case JValue::ARR:
      out.push_back(0xdd);
      put_u32(out, (uint32_t)v.arr.size());
      for (const auto &item : v.arr) encode_value(item, out);
      break;
    case JValue::OBJ:
      out.push_back(0xdf);
      put_u32(out, (uint32_t)v.obj.size());
      for (const auto &kv : v.obj) {
        JValue key;
        key.kind = JValue::STR;
        key.s = kv.first;
        encode_value(key, out);
        encode_value(kv.second, out);
      }
      break;
  }
}

/* ----------------------------------------------------------------------
 * Wire decoding
 * -------------------------------------------------------------------- */

struct WireReader {
  const uint8_t *p;
  const uint8_t *end;
  bool ok = true;

  uint32_t u32() {
    if (end - p < 4) {
      ok = false;
      return 0;
    }
    uint32_t v = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                 ((uint32_t)p[2] << 8) | p[3];
    p += 4;
    return v;
  }

  uint64_t u64() {
    if (end - p < 8) {
      ok = false;
      return 0;
    }
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
    p += 8;
    return v;
  }

  JValue decode() {
    JValue v;
    if (p >= end) {
      ok = false;
      return v;
    }
    uint8_t tag = *p++;
    switch (tag) {
      case 0xc0: return v;
      case 0xc2: v.kind = JValue::BOOL; v.b = false; return v;
      case 0xc3: v.kind = JValue::BOOL; v.b = true; return v;
      case 0xd3: v.kind = JValue::INT; v.i = (int64_t)u64(); return v;
      case 0xcb: {
        v.kind = JValue::FLOAT;
        uint64_t bits = u64();
        memcpy(&v.f, &bits, sizeof v.f);
        return v;
      }
      case 0xdb: {
        v.kind = JValue::STR;
        uint32_t n = u32();
        if ((size_t)(end - p) < n) {
          ok = false;
          return v;
        }
        v.s.assign((const char *)p, n);
        p += n;
        return v;
      }
      case 0xc6: { /* bin32 decoded as string */
        v.kind = JValue::STR;
        uint32_t n = u32();
        if ((size_t)(end - p) < n) {
          ok = false;
          return v;
        }
        v.s.assign((const char *)p, n);
        p += n;
        return v;
      }
      case 0xdd: {
        v.kind = JValue::ARR;
        uint32_t n = u32();
        for (uint32_t i = 0; i < n && ok; ++i)
          v.arr.push_back(decode());
        return v;
      }
      case 0xdf: {
        v.kind = JValue::OBJ;
        uint32_t n = u32();
        for (uint32_t i = 0; i < n && ok; ++i) {
          JValue key = decode();
          JValue val = decode();
          v.obj.emplace_back(std::move(key.s), std::move(val));
        }
        return v;
      }
      default:
        ok = false;
        return v;
    }
  }
};

char *dup_string(const std::string &s) {
  char *out = (char *)malloc(s.size() + 1);
  if (out) memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

int read_exact(int fd, uint8_t *buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = read(fd, buf + got, n - got);
    if (r <= 0) return -1;
    got += (size_t)r;
  }
  return 0;
}

int write_exact(int fd, const uint8_t *buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t w = write(fd, buf + sent, n - sent);
    if (w <= 0) return -1;
    sent += (size_t)w;
  }
  return 0;
}

}  // namespace

/* ----------------------------------------------------------------------
 * C API
 * -------------------------------------------------------------------- */

extern "C" {

int nw_encode_json(const char *json, uint8_t **out, size_t *out_len) {
  if (!json || !out || !out_len) return -1;
  JParser parser(json);
  JValue v = parser.parse_value();
  parser.skip_ws();
  if (!parser.ok || parser.p != parser.end) return -2;
  std::vector<uint8_t> buf;
  encode_value(v, buf);
  *out = (uint8_t *)malloc(buf.size());
  if (!*out) return -3;
  memcpy(*out, buf.data(), buf.size());
  *out_len = buf.size();
  return 0;
}

int nw_decode_to_json(const uint8_t *data, size_t len, char **json_out) {
  if (!data || !json_out) return -1;
  WireReader reader{data, data + len};
  JValue v = reader.decode();
  if (!reader.ok || reader.p != reader.end) return -2;
  std::string out;
  to_json(v, out);
  *json_out = dup_string(out);
  return *json_out ? 0 : -3;
}

int nw_connect(const char *host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    struct hostent *he = gethostbyname(host);
    if (!he) {
      close(fd);
      return -2;
    }
    memcpy(&addr.sin_addr, he->h_addr_list[0], sizeof addr.sin_addr);
  }
  if (connect(fd, (struct sockaddr *)&addr, sizeof addr) != 0) {
    close(fd);
    return -3;
  }
  return fd;
}

int nw_close(int fd) { return close(fd); }

int nw_call_json(int fd, const char *method, const char *body_json,
                 char **response_json) {
  if (fd < 0 || !method || !body_json || !response_json) return -1;

  /* build [method, body] */
  JParser parser(body_json);
  JValue body = parser.parse_value();
  parser.skip_ws();
  if (!parser.ok || parser.p != parser.end) return -2;

  std::vector<uint8_t> payload;
  payload.push_back(0xdd); /* array32 */
  put_u32(payload, 2);
  JValue m;
  m.kind = JValue::STR;
  m.s = method;
  encode_value(m, payload);
  encode_value(body, payload);

  std::vector<uint8_t> frame;
  put_u32(frame, (uint32_t)payload.size());
  frame.insert(frame.end(), payload.begin(), payload.end());
  if (write_exact(fd, frame.data(), frame.size()) != 0) return -4;

  uint8_t lenbuf[4];
  if (read_exact(fd, lenbuf, 4) != 0) return -5;
  uint32_t resp_len = ((uint32_t)lenbuf[0] << 24) |
                      ((uint32_t)lenbuf[1] << 16) |
                      ((uint32_t)lenbuf[2] << 8) | lenbuf[3];
  if (resp_len > (64u << 20)) return -6; /* 64 MiB sanity cap */
  std::vector<uint8_t> resp(resp_len);
  if (resp_len && read_exact(fd, resp.data(), resp_len) != 0) return -5;

  return nw_decode_to_json(resp.data(), resp.size(), response_json);
}

void nw_free(void *ptr) { free(ptr); }

const char *nw_version(void) { return "nomad-tpu-wire/0.1.0"; }

}  /* extern "C" */
