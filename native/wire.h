/* nomad-tpu wire protocol: compact binary codec + framed RPC bridge.
 *
 * This is the cross-language seam of the framework (the role msgpack-RPC
 * over yamux plays in the reference, nomad/rpc.go:335, and the go-plugin
 * gRPC boundary plays for plugins, plugins/base/plugin.go).  A control
 * plane written in any language (Go via cgo, C, C++, Rust) loads this
 * library to talk to the TPU scheduler service:
 *
 *   int fd = nw_connect("127.0.0.1", 4647);
 *   char *resp = NULL;
 *   nw_call_json(fd, "TPUScheduler.ScoreBatch", request_json, &resp);
 *   ...
 *   nw_free(resp);
 *   nw_close(fd);
 *
 * Encoding: a msgpack-compatible subset using the wide fixed forms only
 * (nil c0, false c2, true c3, int64 d3, float64 cb, str32 db, bin32 c6,
 * array32 dd, map32 df), all big-endian.  Frames on the socket are
 * u32(big-endian) length + payload, where payload = array32[method_str,
 * body].  The JSON entry points convert to/from this encoding so callers
 * never build wire values by hand.
 */
#ifndef NOMAD_TPU_WIRE_H
#define NOMAD_TPU_WIRE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Encode a JSON document into wire bytes.  Returns 0 on success; the
 * output buffer is malloc'd and must be released with nw_free. */
int nw_encode_json(const char *json, uint8_t **out, size_t *out_len);

/* Decode wire bytes back into a JSON document (malloc'd). */
int nw_decode_to_json(const uint8_t *data, size_t len, char **json_out);

/* TCP bridge. */
int nw_connect(const char *host, int port);
int nw_close(int fd);

/* One RPC round trip: sends [method, body_json-as-wire], receives the
 * response frame and returns it as JSON.  Returns 0 on success, negative
 * errno-style codes on failure. */
int nw_call_json(int fd, const char *method, const char *body_json,
                 char **response_json);

void nw_free(void *ptr);

/* Library version for fingerprinting. */
const char *nw_version(void);

#ifdef __cplusplus
}
#endif

#endif /* NOMAD_TPU_WIRE_H */
