#!/usr/bin/env bash
# One-shot CI gate: static analysis + analysis self-test + a fast
# tier-1 smoke subset.  Everything here must stay green on every
# commit; the full tier-1 suite (ROADMAP.md) remains the merge gate.
#
#   tools/ci_check.sh            # run everything
#   SMOKE=0 tools/ci_check.sh    # lint + selfcheck only
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== nomadlint: repo-wide run (35 rules, zero findings) =="
python -m tools.nomadlint

echo "== nomadlint: selfcheck (every rule trips its bad fixture) =="
python -m tools.nomadlint --selfcheck

if [ "${SMOKE:-1}" = "1" ]; then
    echo "== tier-1 smoke subset =="
    # the analysis layer's own tests + the TSAN soak + one
    # pipeline-parity file: fast (<2 min), catches wiring breaks;
    # NOT a substitute for the full tier-1 run
    JAX_PLATFORMS=cpu python -m pytest -q \
        -p no:cacheprovider -m 'not slow' \
        tests/test_nomadlint.py \
        tests/test_flowgraph.py \
        tests/test_tsan.py \
        tests/test_stage_accounting.py

    echo "== cluster chaos smoke (3 servers, leader kills + partition) =="
    # leadership-loss gate: zero lost evals / zero duplicate
    # placements vs the fault-free oracle across repeated leader
    # kills and a healed partition; the coreutils timeout kills a
    # wedged cluster so a failover deadlock fails the gate instead
    # of hanging it
    timeout -k 10 300 python -m nomad_tpu.raft.chaos_smoke \
        --jobs 150 --kills 5 --nodes 6

    echo "== follower fan-out bench (1 vs 3 servers, scaled down) =="
    # horizontal-scaling gate: the same storm workload through a
    # 1-server and a 3-server fan-out cluster — zero lost evals,
    # placement-set parity vs the single-server oracle, fan-out
    # actually engaged (follower plans > 0), no leaked remote
    # leases.  Scaled below the BENCH acceptance run (which asserts
    # the >=2x 3v1 speedup at 12x24x512); the kill-timeout fails a
    # wedged cluster instead of hanging the gate
    timeout -k 10 300 python -m nomad_tpu.server.fanout_bench \
        --servers 1,3 --families 120 --jobs-per 1 --nodes 256 \
        --reps 1

    echo "== cluster chaos smoke with fan-out (3 servers) =="
    # leadership-loss gate UNDER fan-out: followers plan through 3
    # leader kills + a healed partition — remote leases die with
    # each leadership, redelivery reclaims them, and the replicated
    # generation fence rejects deposed-leader plans; zero lost, zero
    # duplicates vs the fault-free oracle
    timeout -k 10 300 python -m nomad_tpu.raft.chaos_smoke \
        --jobs 120 --kills 3 --nodes 6 --fanout

    echo "== swarm overload + mass-death SLO smoke (scaled down) =="
    # the overload-graceful control-plane gate: heartbeat storm +
    # concurrent submitters over the real HTTP API with an injected
    # mass node-death — zero lost evals, zero false node-downs,
    # hb >=99.9%, <=2 storm solves, bounded sheds.  Scaled below the
    # acceptance run (2200/1100/500, exercised by bench) to fit the
    # CI budget; the kill-timeout fails a wedged swarm instead of
    # hanging the gate
    timeout -k 10 300 python -m nomad_tpu.loadgen.swarm_smoke \
        --nodes 600 --submitters 240 --death 120 --ttl 8 \
        --base-jobs 150

    echo "== geo federation smoke (2 regions x 3 servers + kill drill) =="
    # the geo-plane gate: a Multiregion job federated both ways with
    # placement parity vs per-region single-region oracles, zero WAN
    # reads for region-local traffic (?region= escape hatch asserted
    # to count), shed submitters redirected to the healthy region
    # within the SLO, and the full region-kill drill — all three east
    # servers dark at once, zero lost evals in west, failed-over
    # submitters landing via their cached retry-region hint, east
    # re-federating after the heal.  The kill-timeout fails a wedged
    # geo plane instead of hanging the gate
    timeout -k 10 300 python -m nomad_tpu.loadgen.geo_smoke \
        --flood-submitters 96 --redirect-slo 20

    echo "== policy-weighted scoring A/B (scaled down) =="
    # the policy-layer gate: heterogeneity-aware throughput must pull
    # placements onto fast nodes and migration-cost stickiness must
    # cut mass-replan churn at equal-or-better aggregate binpack
    # score, both A/B'd against NOMAD_TPU_POLICY=0 on the same world.
    # Scaled below the BENCH acceptance run (which also asserts the
    # <3% identity-weights kernel overhead at f32 — too noisy to gate
    # on a shared CI box); the kill-timeout fails a wedged world
    timeout -k 10 600 env JAX_PLATFORMS=cpu BENCH_POLICY_C=1024 \
        BENCH_POLICY_KERNEL_REPS=40 BENCH_POLICY_NODES=90 \
        BENCH_POLICY_JOBS=24 python -c "
import bench
out = bench.bench_policy()
assert out['throughput']['fast_share_gain'] > 0.2, out['throughput']
assert out['migration']['fewer_migrations'], out['migration']
assert out['migration']['score_delta'] >= 0.0, out['migration']
print('policy gate green:', {
    'fast_share_gain': out['throughput']['fast_share_gain'],
    'migrations_avoided': out['migration']['migrations_avoided'],
    'score_delta': out['migration']['score_delta'],
})
"

    echo "== cluster observability gate (stitching + fan-in, scaled) =="
    # the cluster-scope observability gate: the fan-out workload with
    # the flight recorder A/B'd on/off — trace overhead within the
    # <5% contract (with the unit gate's additive slack), stitched
    # cross-server traces actually produced (spans from >=2 servers
    # on one leader-side waterfall), zero orphan spans, the leader
    # fan-in query answering at 1/3/5 servers, and the metric
    # history ring capped at its configured depth.  Scaled below the
    # BENCH acceptance run; the kill-timeout fails a wedged cluster
    timeout -k 10 600 env JAX_PLATFORMS=cpu BENCH_OBS_FAMILIES=48 \
        BENCH_OBS_NODES=128 BENCH_OBS_REPS=1 python -c "
import bench
out = bench.bench_cluster_obs()
assert out['overhead_ok'], out
assert out['stitched_traces_min'] > 0, out
assert out['orphan_spans'] == 0, out
assert len(out['fanin_query_latency']) == 3, out
assert out['history_ring']['windows'] == 60, out
print('cluster-obs gate green:', {
    'overhead_pct': out['stitched_overhead_pct'],
    'stitched_min': out['stitched_traces_min'],
    'fanin_ms': out['fanin_query_latency'],
})
"

    echo "== control-loop flight-data gate (ledger A/B + site coverage) =="
    # the flight-data gate: decision-ledger overhead A/B within the
    # <3% contract (with the additive slack every overhead gate uses
    # on this shared box), every registered decision site writing
    # records under the swarm + admission-probe + fan-out soak (the
    # decision-ledger lint's non-vacuity proof), and the SLO engine
    # grading a real history ring.  The placement A/B is scaled down;
    # the swarm runs at the same scale as the swarm gate above
    timeout -k 10 600 env JAX_PLATFORMS=cpu BENCH_SLO_NODES=100 \
        BENCH_SLO_JOBS=12 BENCH_SLO_REPS=1 \
        BENCH_SLO_FANOUT_NODES=96 BENCH_SLO_FANOUT_FAMILIES=24 \
        python -c "
import bench
out = bench.bench_slo()
assert out['overhead_ok'], out
assert not out['sites_missing'], out
assert out['swarm_ok'], out
assert len(out['slo_status']['objectives']) >= 5, out
print('slo gate green:', {
    'ledger_overhead_pct': out['ledger_overhead_pct'],
    'sites': sorted(out['site_records']),
    'worst': out['slo_status']['worst'],
})
"

    echo "== 2-process distributed smoke (CPU backend, gloo) =="
    # the multi-host mesh gate: distributed init, pod-mesh chain with
    # zero lost evals, per-host O(dirty rows) cross-host flush, and
    # the sharded storm solve bit-identical to single-device — the
    # launcher kills a deadlocked world at the timeout, so a
    # collective hang fails the gate instead of wedging it
    python -m nomad_tpu.parallel.dist_smoke --procs 2 --timeout 360

    echo "== composed bigworld smoke (fan-out followers x pod mesh) =="
    # the composed-topology gate at reduced scale: a 3-server cluster
    # seeded via the seed_world FSM command, every follower heading a
    # 2-process jax.distributed pod, schedulers ONLY on the fan-out
    # followers — zero lost evals, placement-set parity vs the
    # single-server oracle, pod digest parity on every mesh launch
    # (POD_CHECK), and a killed follower+peer pair catching back up
    # from the dirty-row log.  Scaled well below the BENCH acceptance
    # run (>=1M nodes / >=10M allocs); the kill-timeout fails a
    # wedged world instead of hanging the gate
    timeout -k 10 1800 python -m nomad_tpu.loadgen.bigworld_smoke \
        --nodes 128 --allocs 1024 --jobs 2 --storm-jobs 8 \
        --timeout 900
fi

echo "ci_check: all green"
