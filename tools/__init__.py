"""Repo tooling package (makes ``python -m tools.nomadlint`` work)."""
