"""Cross-module flow core: thread entries, reachable call graphs and
shared-attribute access sets for the concurrency rules.

The batch pipeline is deeply multi-threaded (worker thread, replay
pool, admission, supervisor probe + watchdog sacrificial threads,
background compile threads, broker sweeper, heartbeat sweeper, HTTP
handler threads) and the GIL hides nearly every interleaving from the
CPU tier-1 suite.  This module computes, once per lint run, the facts
the whole-program rules consume:

* **Thread entries** — every function a new thread can start in:
  ``threading.Thread(target=...)`` construction (including nested-def
  targets like the background compile closure), ``*.submit(fn, ...)``
  pool dispatch (``EvaluatePool.submit``), and HTTP handler dispatch
  (``do_*`` methods of ``BaseHTTPRequestHandler`` subclasses — each
  request runs on its own ``ThreadingHTTPServer`` thread).  Spawning
  ``self.run`` dispatches virtually: every scanned subclass override
  is an entry too (``Worker.start`` starts ``BatchWorker.run``).
* **Per-entry call graphs** — reachability from each entry over a
  module-set-wide call graph: ``self.m()`` resolves through the class
  and its scanned bases, bare names through nested defs then module
  functions, and ``obj.m()`` through a globally unique method name
  (the same over-approximation the lock-discipline rule uses).
* **Attribute access sets** — for the shared singletons (``Server``,
  ``BatchWorker``/``Worker``, ``StateStore``, ``EvalBroker``,
  ``DeviceSupervisor``, ``Tracer``/``TRACE``, ``Metrics``): every
  ``self.<attr>`` read/write with the set of locks *guaranteed held*
  at the access — the lexically held locks plus the intersection of
  locks held on every call path from the entry (a guard that only
  SOME paths hold is not a guard).

Lock identity matches the lock-discipline rule's
``<basename>:<Class>.<attr>`` keys so findings from both rules speak
the same vocabulary.  ``threading.Condition(self._x)`` canonicalizes
to the wrapped lock's key (holding the condition IS holding the
lock); a bare ``threading.Condition()`` is its own lock.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .core import Context

# path keys (core.DEFAULT_PATHS) making up the flowgraph module set
FLOW_FILE_KEYS = (
    "batch_worker",
    "plan_apply",
    "server",
    "worker",
    "eval_broker",
    "api_http",
    "trace",
    "telemetry",
    "fanout",
)
FLOW_DIR_KEYS = ("state_dir", "device_dir")

# the shared singletons whose attributes the race detector guards.
# Subclass families collapse onto their root (BatchWorker extends
# Worker: one object at runtime, one attribute namespace here).
SHARED_CLASSES = frozenset(
    {
        "Server",
        "Worker",
        "BatchWorker",
        "StateStore",
        "EvalBroker",
        "DeviceSupervisor",
        "Tracer",
        "Metrics",
    }
)

# names too generic to resolve by global uniqueness: obj.flush() on a
# file object must not resolve to EvalBroker.flush just because no
# other SCANNED class defines one.  Self-calls resolve through the
# class and are unaffected; for foreign-object calls these produce no
# edge (under-approximation on the side of precision — the TSAN
# runtime cross-check covers what static reachability misses).
GENERIC_NAMES = frozenset(
    {
        "flush", "get", "put", "pop", "push", "update", "items",
        "keys", "values", "copy", "close", "read", "write", "send",
        "recv", "clear", "append", "add", "remove", "discard",
        "wait", "notify", "notify_all", "acquire", "release",
        "join", "open", "result", "done", "set", "is_set", "start",
        "stop", "run", "submit", "count", "index", "sort", "next",
        "encode", "decode", "strip", "split", "format", "render",
        "name", "status", "snapshot", "describe", "list",
    }
)

# registration calls whose callable arguments later run on ANOTHER
# thread (the supervisor invokes transition listeners on its probe
# thread AND on whichever worker thread tripped a watchdog; warm
# hooks run on the probe thread during recovery validation).  Each
# registered callable becomes its own entry.
CALLBACK_REGISTRARS = frozenset(
    {"subscribe", "add_warm_hook", "add_done_callback"}
)

# lifecycle methods run on the OPERATOR (main/test) thread — a real
# concurrent participant the spawn scan can't see (nothing spawns
# the main thread).  They share ONE entry group: a single operator
# thread drives start/stop/leadership, so they never race each
# other, but they DO race every spawned thread (stop() flipping
# _running under a live sweeper is exactly the TSAN-observed pair
# that motivated this).
LIFECYCLE_ROOTS = (
    "start",
    "stop",
    "establish_leadership",
    "revoke_leadership",
)

# method calls on self.<attr> that mutate the container in place —
# counted as WRITES to the attribute for race purposes
MUTATING_ATTRS = frozenset(
    {
        "append",
        "appendleft",
        "extend",
        "insert",
        "add",
        "discard",
        "remove",
        "pop",
        "popleft",
        "popitem",
        "clear",
        "update",
        "setdefault",
        "put",
        "acquire",
        "release",
    }
)


@dataclass(frozen=True)
class Access:
    """One ``self.<attr>`` touch inside a method body."""

    attr: str
    kind: str  # "r" | "w"
    line: int
    held: FrozenSet[str]  # lock keys lexically held at the site


@dataclass(frozen=True)
class CallSite:
    name: str  # bare callee name (attr or function name)
    on_self: bool  # self.name(...) — resolve through the class
    line: int
    held: FrozenSet[str]
    dotted: Optional[str] = None  # full a.b.c chain when resolvable
    recv_attr: Optional[str] = None  # X of self.X.name(...)


@dataclass(frozen=True)
class SpawnSite:
    """A thread-entry creation: Thread(target=...) or pool submit."""

    target: str  # bare target name
    on_self: bool
    kind: str  # "thread" | "pool"
    line: int
    label: Optional[str]  # Thread name= constant when present


@dataclass
class MethodInfo:
    qualname: str  # "Class.method" / "module:func" / "outer.<nested>"
    cls: Optional[str]
    name: str
    path: str
    lineno: int
    accesses: List[Access] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    spawns: List[SpawnSite] = field(default_factory=list)
    # names of nested defs declared in this body (for resolution)
    nested: Dict[str, str] = field(default_factory=dict)
    # local name -> (method name, via_self) for ``x = self._m`` and
    # ``x = getattr(obj, "m", ...)`` aliases (spawn-target support)
    local_refs: Dict[str, Tuple[str, bool]] = field(
        default_factory=dict
    )


@dataclass(frozen=True)
class Entry:
    """A thread entrypoint: the function a fresh thread starts in.

    ``group`` models instance-concurrency: virtual-dispatch siblings
    of ONE spawn site (``Worker.start`` starting ``self.run`` covers
    ``Worker.run`` and ``BatchWorker.run``) share a group — a given
    instance runs exactly one of them, so same-group entries never
    race against each other on ``self``.  ``multi`` marks entries
    that can run CONCURRENTLY WITH THEMSELVES against one shared
    object (HTTP handlers on a ThreadingHTTPServer, pool submits):
    those conflict with their own group too."""

    key: str  # unique id, e.g. "thread:BatchWorker.run"
    method: str  # qualname of the entry method
    kind: str  # "thread" | "pool" | "http"
    spawned_at: str  # "path:line" of the spawning site
    label: Optional[str]  # thread name when statically known
    group: str = ""  # spawn-site identity (virtual siblings share)
    multi: bool = False  # may self-overlap on one shared object

    def render(self) -> str:
        tag = f" [{self.label}]" if self.label else ""
        return f"{self.method} ({self.kind}{tag})"


def entries_conflict(a: Entry, b: Entry) -> bool:
    """Whether two entries can touch ONE object concurrently: any
    two distinct spawn groups can; a group overlaps itself only when
    the entry is ``multi`` (HTTP / pool fan-out)."""
    if a.group != b.group:
        return True
    return a.multi or b.multi


@dataclass(frozen=True)
class AttrSite:
    """One access to a shared attribute, entry-qualified."""

    entry: Entry
    method: str
    path: str
    line: int
    kind: str  # "r" | "w"
    guards: FrozenSet[str]  # locks guaranteed held at the access


class FlowGraph:
    """The computed whole-program view.  Build with
    :func:`build_flowgraph`; rules consume the tables below.

    * ``entries`` — every discovered thread entry
    * ``locks`` — lock key -> reentrant? (Condition keys collapsed)
    * ``shared_access`` — (family, attr) -> [AttrSite, ...]
    * ``methods`` — qualname -> MethodInfo
    """

    def __init__(self) -> None:
        self.entries: List[Entry] = []
        self.locks: Dict[str, bool] = {}
        self.methods: Dict[str, MethodInfo] = {}
        self.shared_access: Dict[
            Tuple[str, str], List[AttrSite]
        ] = {}
        # family -> class names collapsed into it
        self.families: Dict[str, List[str]] = {}
        # per-entry reachable method qualnames (incl. entry itself)
        self.reachable: Dict[str, Set[str]] = {}
        # per-entry, per-method locks guaranteed held ON ENTRY to the
        # method (intersection over all call paths from the entry)
        self.held_in: Dict[str, Dict[str, FrozenSet[str]]] = {}
        # blocking-op closure: qualname -> {op: witness-path} of
        # blocking calls reachable from the method (transitive);
        # the witness names the call chain for findings
        self.blocking: Dict[str, Dict[str, str]] = {}


# -- class table -------------------------------------------------------


def _flow_files(ctx: Context) -> List[str]:
    override = ctx.overrides.get("scan_files")
    if override is not None:
        return list(override)
    files = [ctx.path(k) for k in FLOW_FILE_KEYS]
    for dir_key in FLOW_DIR_KEYS:
        root = ctx.path(dir_key)
        files.extend(
            os.path.join(root, fn)
            for fn in sorted(os.listdir(root))
            if fn.endswith(".py") and fn != "__init__.py"
        )
    return [f for f in files if os.path.exists(f)]


def _lock_attrs(cls: ast.ClassDef) -> Dict[str, Tuple[bool, Optional[str]]]:
    """lock attr -> (reentrant?, wrapped_attr).  ``wrapped_attr`` is
    set for ``threading.Condition(self._x)`` — acquiring the condition
    acquires ``self._x``."""
    out: Dict[str, Tuple[bool, Optional[str]]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in ("Lock", "RLock", "Condition")
        ):
            continue
        wrapped: Optional[str] = None
        if call.func.attr == "Condition" and call.args:
            first = call.args[0]
            if (
                isinstance(first, ast.Attribute)
                and isinstance(first.value, ast.Name)
                and first.value.id == "self"
            ):
                wrapped = first.attr
        reentrant = call.func.attr == "RLock"
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out[t.attr] = (reentrant, wrapped)
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _self_root_attr(node: ast.AST) -> Optional[str]:
    """The first attribute off ``self`` in a chain like
    ``self.x.y[k].z`` (-> ``x``); None when not self-rooted."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        attr = _self_attr(node)
        if attr is not None:
            return attr
        node = node.value
    return None


class _BodyScanner:
    """Walks one function body recording accesses/calls/spawns with
    the lexically-held lock stack.  Nested defs get their OWN
    MethodInfo (they run later, possibly on another thread) — the
    parent records them in ``nested`` for name resolution and spawn
    targets."""

    def __init__(
        self,
        fn: ast.AST,
        info: MethodInfo,
        lock_keys: Dict[str, str],
        sink: Dict[str, MethodInfo],
        data_attrs: Set[str],
    ) -> None:
        self.info = info
        self.lock_keys = lock_keys  # self attr -> canonical lock key
        self.sink = sink
        self.data_attrs = data_attrs
        self._walk_body(fn, frozenset())

    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None:
            return self.lock_keys.get(attr)
        return None

    def _note_access(
        self, attr: str, kind: str, line: int, held: FrozenSet[str]
    ) -> None:
        if attr in self.data_attrs:
            self.info.accesses.append(
                Access(attr=attr, kind=kind, line=line, held=held)
            )

    def _callable_ref(
        self, expr: ast.AST
    ) -> Optional[Tuple[str, bool]]:
        """(name, on_self) for a callable-reference expression:
        ``self._m``, a local aliasing one, or ``getattr(x, "m")``."""
        attr = _self_attr(expr)
        if attr is not None:
            return (attr, True)
        if isinstance(expr, ast.Name):
            ref = self.info.local_refs.get(expr.id)
            if ref is not None:
                return ref
            return (expr.id, False)
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Name)
            and expr.func.id == "getattr"
            and len(expr.args) >= 2
            and isinstance(expr.args[1], ast.Constant)
            and isinstance(expr.args[1].value, str)
        ):
            return (expr.args[1].value, False)
        return None

    def _spawns_from_call(
        self, call: ast.Call
    ) -> List[SpawnSite]:
        fn = call.func
        out: List[SpawnSite] = []
        # threading.Thread(target=X, args=(...), name="...") — the
        # target AND any callable passed through args runs on the
        # new thread (Server hands each worker's warm_shapes to the
        # warmup thread this way)
        is_thread = (
            isinstance(fn, ast.Attribute) and fn.attr == "Thread"
        ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
        if is_thread:
            target = None
            label = None
            extra: List[ast.AST] = []
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "name" and isinstance(
                    kw.value, ast.Constant
                ):
                    label = str(kw.value.value)
                elif kw.arg == "args" and isinstance(
                    kw.value, (ast.Tuple, ast.List)
                ):
                    extra.extend(kw.value.elts)
            if target is None:
                return out
            for expr in [target] + extra:
                ref = self._callable_ref(expr)
                if ref is not None:
                    out.append(
                        SpawnSite(
                            ref[0], ref[1], "thread",
                            call.lineno, label,
                        )
                    )
            return out
        if not isinstance(fn, ast.Attribute) or not call.args:
            return out
        # pool.submit(fn, ...): only resolvable first args count —
        # the generic forwarding inside EvaluatePool.submit passes a
        # parameter through, which the OUTER call site resolves
        if fn.attr == "submit":
            ref = self._callable_ref(call.args[0])
            if ref is not None:
                out.append(
                    SpawnSite(
                        ref[0], ref[1], "pool", call.lineno, None
                    )
                )
            return out
        # callback registration: the registered callable later runs
        # on the registrar's thread(s) — its own entry
        if fn.attr in CALLBACK_REGISTRARS:
            for arg in call.args:
                ref = self._callable_ref(arg)
                if ref is not None:
                    out.append(
                        SpawnSite(
                            ref[0], ref[1], "callback",
                            call.lineno, fn.attr,
                        )
                    )
        return out

    def _walk_body(
        self, node: ast.AST, held: FrozenSet[str]
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # nested def: its body runs later (often on another
                # thread) — scanned as its own method
                nested_qual = (
                    f"{self.info.qualname}.<{child.name}>"
                )
                self.info.nested[child.name] = nested_qual
                sub = MethodInfo(
                    qualname=nested_qual,
                    cls=self.info.cls,
                    name=child.name,
                    path=self.info.path,
                    lineno=child.lineno,
                )
                self.sink[nested_qual] = sub
                scanner = _BodyScanner.__new__(_BodyScanner)
                scanner.info = sub
                scanner.lock_keys = self.lock_keys
                scanner.sink = self.sink
                scanner.data_attrs = self.data_attrs
                scanner._walk_body(child, frozenset())
                # parent nesteds are resolvable from the child too
                sub.nested.update(self.info.nested)
                continue
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                inner = held
                for item in child.items:
                    key = self._lock_key(item.context_expr)
                    if key is not None:
                        inner = inner | {key}
                # the with-items themselves evaluate under the OUTER
                # hold; attr reads there (self._lock) are lock attrs,
                # not data attrs, so just descend into the body
                self._walk_body(child, inner)
                continue
            self._visit(child, held)
            self._walk_body(child, held)

    def _visit(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is None:
                # self.x.y = v / del self.x.y: a store through a
                # sub-object mutates the object x holds — a WRITE
                # on x, same as the Subscript case below (the inner
                # self.x Load is additionally recorded by the walk)
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    root = _self_root_attr(node.value)
                    if (
                        root is not None
                        and root not in self.lock_keys
                    ):
                        self._note_access(
                            root, "w", node.lineno, held
                        )
                return
            if attr in self.lock_keys:
                return
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self._note_access(attr, "w", node.lineno, held)
            else:
                self._note_access(attr, "r", node.lineno, held)
            return
        if isinstance(node, ast.Subscript):
            # self.x[k] = v / del self.x[k]: mutation of x
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                root = _self_root_attr(node.value)
                if (
                    root is not None
                    and root not in self.lock_keys
                ):
                    self._note_access(
                        root, "w", node.lineno, held
                    )
            return
        if isinstance(node, ast.Assign):
            # local callable aliases: x = self._m / getattr(o, "m")
            if len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                ref = self._callable_ref(node.value)
                if ref is not None and (
                    _self_attr(node.value) is not None
                    or isinstance(node.value, ast.Call)
                ):
                    self.info.local_refs[
                        node.targets[0].id
                    ] = ref
            return
        if not isinstance(node, ast.Call):
            return
        self.info.spawns.extend(self._spawns_from_call(node))
        fn = node.func
        if isinstance(fn, ast.Attribute):
            base_attr = _self_attr(fn.value)
            if base_attr is not None and fn.attr in MUTATING_ATTRS:
                # self.x.append(...) mutates x in place
                self._note_access(
                    base_attr, "w", node.lineno, held
                )
            on_self = (
                isinstance(fn.value, ast.Name)
                and fn.value.id == "self"
            )
            self.info.calls.append(
                CallSite(
                    fn.attr,
                    on_self,
                    node.lineno,
                    held,
                    dotted=_dotted(fn),
                    recv_attr=base_attr,
                )
            )
        elif isinstance(fn, ast.Name):
            self.info.calls.append(
                CallSite(
                    fn.id, False, node.lineno, held, dotted=fn.id
                )
            )


# -- graph construction ------------------------------------------------


def _class_defs(
    ctx: Context, files: Iterable[str]
) -> List[Tuple[str, ast.ClassDef]]:
    out = []
    for path in files:
        for node in ctx.tree(path).body:
            if isinstance(node, ast.ClassDef):
                out.append((path, node))
    return out


def _family_of(
    cls_name: str, bases: Dict[str, List[str]]
) -> str:
    """Topmost scanned base (BatchWorker -> Worker); cycles cannot
    occur in Python inheritance."""
    cur = cls_name
    while True:
        parents = [b for b in bases.get(cur, []) if b in bases]
        if not parents:
            return cur
        cur = parents[0]


# blocking-op vocabulary (blocking-while-locked): operations that can
# park a thread for unbounded (or device-scale) time.  A Condition
# ``.wait`` on the HELD lock itself releases it — the one blocking
# call that is safe (and idiomatic) under its own lock.
BLOCKING_DOTTED_PREFIXES = (
    "time.sleep",
    "_time.sleep",
    "jax.block_until_ready",
    "_jax.block_until_ready",
    "jax.device_get",
    "jax.device_put",
    "socket.",
    "requests.",
    "urllib.",
)
BLOCKING_ATTRS = frozenset(
    {
        "block_until_ready",
        "device_get",
        "device_put",
        "recv",
        "accept",
        "urlopen",
        "read_response",
    }
)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def blocking_op(
    call: CallSite, lock_attr_names: Set[str]
) -> Optional[str]:
    """A human-readable blocking-op name when ``call`` can park the
    calling thread for unbounded (or device-scale) time; None when it
    cannot.  A ``.wait()`` on a lock/condition attribute is exempt —
    a Condition.wait RELEASES the lock it wraps, so waiting under its
    own lock is the idiom, not a wedge."""
    if call.dotted:
        for prefix in BLOCKING_DOTTED_PREFIXES:
            if call.dotted == prefix.rstrip(
                "."
            ) or call.dotted.startswith(prefix):
                return f"{call.dotted}()"
    if call.name in BLOCKING_ATTRS:
        return f".{call.name}()"
    if call.name == "wait" and call.recv_attr is not None:
        if call.recv_attr in lock_attr_names:
            return None
        return f"self.{call.recv_attr}.wait()"
    return None


def build_flowgraph(ctx: Context) -> FlowGraph:
    """Parse the flow module set and compute the whole-program
    tables.  Pure function of the Context (tests substitute fixture
    files through ``scan_files`` overrides)."""
    g = FlowGraph()
    files = _flow_files(ctx)
    classes = _class_defs(ctx, files)

    # inheritance families (scanned classes only)
    bases: Dict[str, List[str]] = {}
    for _path, cls in classes:
        bases[cls.name] = [
            b.id for b in cls.bases if isinstance(b, ast.Name)
        ]
    family: Dict[str, str] = {
        name: _family_of(name, bases) for name in bases
    }
    for name, fam in family.items():
        g.families.setdefault(fam, []).append(name)

    # lock tables per family; canonical keys use the DEFINING class
    lock_keys_by_class: Dict[str, Dict[str, str]] = {}
    for path, cls in classes:
        base = os.path.basename(path)
        attrs = _lock_attrs(cls)
        keys: Dict[str, str] = {}
        for attr, (reentrant, wrapped) in attrs.items():
            canonical = wrapped if wrapped in attrs else attr
            key = f"{base}:{cls.name}.{canonical}"
            keys[attr] = key
            g.locks.setdefault(
                key, attrs[canonical][0] if wrapped else reentrant
            )
        lock_keys_by_class[cls.name] = keys
    # subclasses see base-class locks (self._lock in a BatchWorker
    # method is Worker's lock when Worker defined it)
    for name in bases:
        merged: Dict[str, str] = {}
        chain = [name]
        cur = name
        while True:
            parents = [
                b for b in bases.get(cur, []) if b in bases
            ]
            if not parents:
                break
            cur = parents[0]
            chain.append(cur)
        for cls_name in reversed(chain):
            merged.update(lock_keys_by_class.get(cls_name, {}))
        lock_keys_by_class[name] = merged

    # data attributes per family (anything assigned via self.<attr>)
    data_attrs_by_family: Dict[str, Set[str]] = {}
    for path, cls in classes:
        fam = family[cls.name]
        attrs = data_attrs_by_family.setdefault(fam, set())
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    a = _self_attr(t)
                    if a is not None:
                        attrs.add(a)
                    elif isinstance(
                        t, (ast.Subscript, ast.Attribute)
                    ):
                        base_a = _self_attr(
                            getattr(t, "value", None)
                        )
                        if base_a is not None:
                            attrs.add(base_a)
            elif isinstance(
                node, (ast.AugAssign, ast.AnnAssign)
            ):
                a = _self_attr(node.target)
                if a is not None:
                    attrs.add(a)
    # lock attrs are modelled as locks, not data (their replacement
    # is the lock-discipline rule's business); Event attrs are sync
    # primitives with their own internal lock — set/clear/wait on
    # them is signalling, not shared data
    for path, cls in classes:
        fam = family[cls.name]
        for attr in lock_keys_by_class.get(cls.name, ()):
            data_attrs_by_family.get(fam, set()).discard(attr)
        for node in ast.walk(cls):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "Event"
            ):
                for t in node.targets:
                    a = _self_attr(t)
                    if a is not None:
                        data_attrs_by_family.get(
                            fam, set()
                        ).discard(a)

    # scan every method (+ module functions) into MethodInfo
    by_name: Dict[str, List[MethodInfo]] = {}
    by_class: Dict[Tuple[str, str], MethodInfo] = {}
    # fixture runs (scan_files override) track every class: the
    # synthetic two-thread fixtures don't impersonate production
    # class names
    track_all = "scan_files" in ctx.overrides
    for path, cls in classes:
        fam = family[cls.name]
        shared = (
            track_all
            or fam in SHARED_CLASSES
            or cls.name in SHARED_CLASSES
        )
        data_attrs = (
            data_attrs_by_family.get(fam, set()) if shared else set()
        )
        for node in cls.body:
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            qual = f"{cls.name}.{node.name}"
            info = MethodInfo(
                qualname=qual,
                cls=cls.name,
                name=node.name,
                path=path,
                lineno=node.lineno,
            )
            g.methods[qual] = info
            _BodyScanner(
                node,
                info,
                lock_keys_by_class.get(cls.name, {}),
                g.methods,
                data_attrs,
            )
            by_class[(cls.name, node.name)] = info
            by_name.setdefault(node.name, []).append(info)
    for path in files:
        base = os.path.basename(path)
        for node in ctx.tree(path).body:
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            qual = f"{base}:{node.name}"
            info = MethodInfo(
                qualname=qual,
                cls=None,
                name=node.name,
                path=path,
                lineno=node.lineno,
            )
            g.methods[qual] = info
            _BodyScanner(node, info, {}, g.methods, set())
            by_name.setdefault(node.name, []).append(info)
    # subclass map for virtual dispatch on self-spawns
    subclasses: Dict[str, List[str]] = {}
    for name, parents in bases.items():
        for p in parents:
            if p in bases:
                subclasses.setdefault(p, []).append(name)

    def resolve(
        site_cls: Optional[str], call: CallSite, info: MethodInfo
    ) -> Optional[MethodInfo]:
        """One callee for a call site, or None (unresolvable /
        ambiguous — over-approximation stops there)."""
        if call.name in info.nested:
            return g.methods.get(info.nested[call.name])
        if call.on_self and site_cls is not None:
            cur: Optional[str] = site_cls
            while cur is not None:
                hit = by_class.get((cur, call.name))
                if hit is not None:
                    return hit
                parents = [
                    b for b in bases.get(cur, []) if b in bases
                ]
                cur = parents[0] if parents else None
        if call.name in GENERIC_NAMES:
            return None
        cands = by_name.get(call.name, [])
        real = [c for c in cands if "<" not in c.qualname]
        if len(real) == 1:
            return real[0]
        return None

    def resolve_spawn(
        info: MethodInfo, spawn: SpawnSite
    ) -> List[MethodInfo]:
        """Entry methods a spawn can start — virtual dispatch on
        self-targets (Worker.start spawning self.run also starts
        every scanned override)."""
        out: List[MethodInfo] = []
        if spawn.target in info.nested:
            hit = g.methods.get(info.nested[spawn.target])
            return [hit] if hit is not None else []
        if spawn.on_self and info.cls is not None:
            roots = [info.cls] + [
                sub
                for sub in _all_subclasses(info.cls, subclasses)
            ]
            for cls_name in roots:
                cur: Optional[str] = cls_name
                while cur is not None:
                    hit = by_class.get((cur, spawn.target))
                    if hit is not None:
                        if hit not in out:
                            out.append(hit)
                        break
                    parents = [
                        b
                        for b in bases.get(cur, [])
                        if b in bases
                    ]
                    cur = parents[0] if parents else None
            return out
        cands = [
            c
            for c in by_name.get(spawn.target, [])
            if "<" not in c.qualname
        ]
        if len(cands) == 1:
            return cands
        return []

    # -- thread entries ------------------------------------------------
    seen_entries: Set[Tuple[str, str]] = set()
    for info in list(g.methods.values()):
        for spawn in info.spawns:
            for target in resolve_spawn(info, spawn):
                key = (spawn.kind, target.qualname)
                if key in seen_entries:
                    continue
                seen_entries.add(key)
                site = (
                    f"{os.path.basename(info.path)}:{spawn.line}"
                )
                g.entries.append(
                    Entry(
                        key=f"{spawn.kind}:{target.qualname}",
                        method=target.qualname,
                        kind=spawn.kind,
                        spawned_at=site,
                        label=spawn.label,
                        group=site,
                        # pool submits fan out concurrently; a
                        # registered callback can be invoked from
                        # SEVERAL threads at once (the supervisor
                        # fires listeners from its probe thread AND
                        # from whichever worker thread tripped a
                        # watchdog) — both self-overlap
                        multi=spawn.kind in ("pool", "callback"),
                    )
                )
    # HTTP handler dispatch: each request runs on its own thread
    for path, cls in classes:
        if not any(
            isinstance(b, ast.Name)
            and b.id == "BaseHTTPRequestHandler"
            for b in cls.bases
        ):
            continue
        for node in cls.body:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name.startswith("do_"):
                qual = f"{cls.name}.{node.name}"
                if ("http", qual) not in seen_entries:
                    seen_entries.add(("http", qual))
                    g.entries.append(
                        Entry(
                            key=f"http:{qual}",
                            method=qual,
                            kind="http",
                            spawned_at=(
                                f"{os.path.basename(path)}:"
                                f"{node.lineno}"
                            ),
                            label=node.name,
                            group=f"http:{qual}",
                            multi=True,
                        )
                    )
    # operator-thread lifecycle entries (shared classes only)
    for path, cls in classes:
        fam = family[cls.name]
        if not (
            track_all
            or fam in SHARED_CLASSES
            or cls.name in SHARED_CLASSES
        ):
            continue
        for node in cls.body:
            if (
                isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                )
                and node.name in LIFECYCLE_ROOTS
            ):
                qual = f"{cls.name}.{node.name}"
                if ("main", qual) in seen_entries:
                    continue
                seen_entries.add(("main", qual))
                g.entries.append(
                    Entry(
                        key=f"main:{qual}",
                        method=qual,
                        kind="main",
                        spawned_at=(
                            f"{os.path.basename(path)}:"
                            f"{node.lineno}"
                        ),
                        label="lifecycle",
                        group="main",
                        multi=False,
                    )
                )
    g.entries.sort(key=lambda e: e.key)

    # -- per-entry reachability + guaranteed-held dataflow -------------
    for entry in g.entries:
        held_in: Dict[str, FrozenSet[str]] = {
            entry.method: frozenset()
        }
        work = [entry.method]
        reach = {entry.method}
        while work:
            qual = work.pop()
            info = g.methods.get(qual)
            if info is None:
                continue
            incoming = held_in.get(qual, frozenset())
            for call in info.calls:
                callee = resolve(info.cls, call, info)
                if callee is None:
                    continue
                at_callee = incoming | call.held
                prev = held_in.get(callee.qualname)
                if prev is None:
                    held_in[callee.qualname] = frozenset(at_callee)
                    reach.add(callee.qualname)
                    work.append(callee.qualname)
                else:
                    merged = prev & at_callee
                    if merged != prev:
                        held_in[callee.qualname] = merged
                        work.append(callee.qualname)
        g.reachable[entry.key] = reach
        g.held_in[entry.key] = held_in

    # -- shared attribute access sets ----------------------------------
    for entry in g.entries:
        held_in = g.held_in[entry.key]
        for qual in g.reachable[entry.key]:
            info = g.methods.get(qual)
            if info is None or info.cls is None:
                continue
            fam = family.get(info.cls, info.cls)
            if not track_all and (
                fam not in SHARED_CLASSES
                and info.cls not in SHARED_CLASSES
            ):
                continue
            # constructor-time writes happen-before thread start
            if info.name == "__init__":
                continue
            base_held = held_in.get(qual, frozenset())
            for acc in info.accesses:
                g.shared_access.setdefault(
                    (fam, acc.attr), []
                ).append(
                    AttrSite(
                        entry=entry,
                        method=qual,
                        path=info.path,
                        line=acc.line,
                        kind=acc.kind,
                        guards=acc.held | base_held,
                    )
                )

    # -- blocking closure ----------------------------------------------
    lock_attr_names: Set[str] = set()
    for keys in lock_keys_by_class.values():
        lock_attr_names |= set(keys)
    g.lock_attr_names = lock_attr_names  # type: ignore[attr-defined]
    for qual, info in g.methods.items():
        ops: Dict[str, str] = {}
        for call in info.calls:
            op = blocking_op(call, lock_attr_names)
            if op is not None:
                ops.setdefault(
                    op, f"{op} at line {call.line}"
                )
        g.blocking[qual] = ops
    changed = True
    while changed:
        changed = False
        for qual, info in g.methods.items():
            acc = g.blocking[qual]
            for call in info.calls:
                callee = resolve(info.cls, call, info)
                if callee is None:
                    continue
                for op, path in g.blocking.get(
                    callee.qualname, {}
                ).items():
                    if op not in acc:
                        acc[op] = f"{callee.qualname} -> {path}"
                        changed = True
    # `resolve` is closed over the run's tables — expose it for the
    # rules (blocking-while-locked re-resolves call sites)
    g.resolve = resolve  # type: ignore[attr-defined]
    return g


def _all_subclasses(
    name: str, subclasses: Dict[str, List[str]]
) -> List[str]:
    out: List[str] = []
    stack = list(subclasses.get(name, []))
    while stack:
        cur = stack.pop()
        if cur in out:
            continue
        out.append(cur)
        stack.extend(subclasses.get(cur, []))
    return out


# -- cached per-context build -----------------------------------------


def flowgraph(ctx: Context) -> FlowGraph:
    """Context-cached build: the concurrency rules (and the CLI dump)
    share one graph per lint run.  Cached on the Context itself so a
    recycled object id can never alias a stale graph."""
    g = getattr(ctx, "_flowgraph_cache", None)
    if g is None:
        g = build_flowgraph(ctx)
        ctx._flowgraph_cache = g  # type: ignore[attr-defined]
    return g


# -- operator dump (feeds docs/ARCHITECTURE.md "Concurrency model") ----


def render_dump(g: FlowGraph, repo: str) -> str:
    """Deterministic markdown rendering of the flowgraph: thread
    entries, lock table, shared attributes and their guards.  The
    docs/ARCHITECTURE.md "Concurrency model" section embeds this
    verbatim (concurrency-doc rule), so the doc cannot drift from the
    analysis."""
    lines: List[str] = []
    lines.append("**Thread entries** (who starts code where):")
    lines.append("")
    for e in g.entries:
        lines.append(
            f"- `{e.method}` — {e.kind}"
            + (f" `{e.label}`" if e.label else "")
            + f", spawned at `{e.spawned_at}`"
        )
    lines.append("")
    lines.append("**Locks**:")
    lines.append("")
    for key in sorted(g.locks):
        kind = "RLock" if g.locks[key] else "Lock"
        lines.append(f"- `{key}` ({kind})")
    lines.append("")
    lines.append(
        "**Shared attributes** (written from one thread entry and "
        "touched from another; guard = lock held at every access, "
        "`unguarded` = allowlisted in "
        "tools/nomadlint/rules/concurrency.py):"
    )
    lines.append("")
    for (fam, attr) in sorted(g.shared_access):
        sites = g.shared_access[(fam, attr)]
        # same pair test as shared-state-guard: a write from one
        # entry and a touch from a CONFLICTING entry (same-group
        # virtual siblings never overlap on one instance) — attrs
        # without such a pair are not shared state and would make
        # the `unguarded = allowlisted` legend a lie
        if not any(
            a.kind == "w" and entries_conflict(a.entry, b.entry)
            for a in sites
            for b in sites
        ):
            continue
        entries = sorted({s.entry.method for s in sites})
        common = None
        for s in sites:
            common = (
                set(s.guards)
                if common is None
                else common & set(s.guards)
            )
        guard = (
            f"`{sorted(common)[0]}`"
            if common
            else "unguarded"
        )
        lines.append(
            f"- `{fam}.{attr}` — touched by "
            f"{len(entries)} entries "
            f"({', '.join(f'`{e}`' for e in entries[:4])}"
            + (", …" if len(entries) > 4 else "")
            + f"); guard: {guard}"
        )
    lines.append("")
    return "\n".join(lines)
