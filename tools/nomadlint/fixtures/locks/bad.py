"""lock-discipline bad fixture: ABBA ordering cycle, a non-reentrant
self-nest, and an unallowlisted lock replacement."""
import threading


class Worker:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def backward(self):
        # BAD: opposite order to forward() — ABBA deadlock window
        with self._b_lock:
            with self._a_lock:
                pass

    def nested_self(self):
        with self._a_lock:
            # BAD: non-reentrant Lock acquired while held
            with self._a_lock:
                pass

    def reset(self):
        # BAD: replacing a lock outside __init__ without an
        # ALLOWLIST entry
        self._a_lock = threading.Lock()
