"""lock-discipline clean fixture: consistent ordering, reentrant
self-nesting only, locks created in __init__ alone."""
import threading


class Worker:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._r_lock = threading.RLock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def forward_again(self):
        with self._a_lock:
            self._leaf()

    def _leaf(self):
        with self._b_lock:
            pass

    def reentrant(self):
        with self._r_lock:
            with self._r_lock:
                pass
