"""shared-state-guard bad fixture: a two-thread object with one
properly guarded attribute and one raced one — the rule must flag
``racy`` (written from the loop thread, read from the poke thread,
no common lock) and stay quiet about ``guarded``."""
import threading


class Thing:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.guarded = 0
        self.racy = 0

    def start(self) -> None:
        threading.Thread(
            target=self._loop, name="loop", daemon=True
        ).start()
        threading.Thread(
            target=self._poker, name="poker", daemon=True
        ).start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                self.guarded += 1
            self.racy += 1

    def _poker(self) -> None:
        while True:
            with self._lock:
                if self.guarded > 10:
                    self.guarded = 0
            if self.racy > 10:
                self.racy = 0
