"""shared-state-guard clean fixture: every cross-thread attribute
shares one lock across all access sites."""
import threading


class Thing:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.guarded = 0
        self.other = 0

    def start(self) -> None:
        threading.Thread(
            target=self._loop, name="loop", daemon=True
        ).start()
        threading.Thread(
            target=self._poker, name="poker", daemon=True
        ).start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                self.guarded += 1
                self.other = self.guarded * 2

    def _poker(self) -> None:
        while True:
            with self._lock:
                if self.guarded > 10:
                    self.guarded = 0
                    self.other = 0
