"""kernel-contract clean fixture: distinct rungs, closed dtypes,
and declared multi-host + fan-out pod ladders."""
import jax
import numpy as np

from nomad_tpu.ops.contracts import KernelContract

MESH_HOST_WIDTHS = (8, 16)
MESH_FANOUT_WIDTHS = (2, 4)


def _kernel():
    return jax.jit(lambda x: x * np.float32(2.0))


def iter_contracts():
    sds = jax.ShapeDtypeStruct
    return [
        KernelContract(
            name="steady",
            kernel=_kernel,
            ladder=[
                ((sds((4,), np.float32),), {}),
                ((sds((8,), np.float32),), {}),
            ],
            out_dtypes=frozenset({"float32"}),
        )
    ]
