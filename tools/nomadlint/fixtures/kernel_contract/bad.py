"""kernel-contract bad fixture: a ladder whose two rungs collapse
onto ONE compiled signature, whose output dtype escapes the declared
closure — and NO multi-host pod ladder (no MESH_HOST_WIDTHS), so pod
recompiles could drift silently."""
import jax
import numpy as np

from nomad_tpu.ops.contracts import KernelContract


def _kernel():
    return jax.jit(lambda x: x * 2.0)


def iter_contracts():
    sds = jax.ShapeDtypeStruct
    rung = ((sds((8,), np.float32),), {})
    return [
        KernelContract(
            name="drifty",
            kernel=_kernel,
            # duplicate rungs: declared ladder of 2, ONE signature
            ladder=[rung, rung],
            # kernel outputs float32 — escapes this closure
            out_dtypes=frozenset({"int32"}),
        )
    ]
