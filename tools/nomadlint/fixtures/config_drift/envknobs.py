"""config-drift fixture registry."""
ENV_KNOBS = {
    "NOMAD_TPU_GOOD_KNOB": ("1", "fixture.py", "a documented knob"),
}
