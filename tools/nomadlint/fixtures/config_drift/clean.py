"""config-drift clean fixture: every knob read is registered and
documented."""
import os

GOOD = os.environ.get("NOMAD_TPU_GOOD_KNOB", "1")
