"""config-drift bad fixture: reads a knob the registry and docs
don't know."""
import os

GOOD = os.environ.get("NOMAD_TPU_GOOD_KNOB", "1")
# BAD: unregistered, undocumented
ROGUE = os.environ.get("NOMAD_TPU_ROGUE_KNOB", "0")
