"""blocking-while-locked clean fixture: blocking ops run outside the
critical section, and a Condition waits under its own lock (which it
releases — the idiom, not a wedge)."""
import threading
import time

import jax


class Thing:
    def __init__(self) -> None:
        self._lock = threading.Condition()
        self.value = None

    def poll(self) -> None:
        with self._lock:
            self._lock.wait(0.1)
        time.sleep(0.01)

    def refresh(self) -> None:
        with self._lock:
            stale = self.value
        fresh = jax.device_get(stale)
        with self._lock:
            self.value = fresh
