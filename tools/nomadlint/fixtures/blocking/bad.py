"""blocking-while-locked bad fixture: a direct ``time.sleep`` under
a lock, and a transitive one — a lock-holding call reaching a
blocking device fetch two frames down."""
import threading
import time

import jax


class Thing:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.value = None

    def direct(self) -> None:
        with self._lock:
            time.sleep(0.5)

    def transitive(self) -> None:
        with self._lock:
            self._refresh()

    def _refresh(self) -> None:
        self._fetch_cols()

    def _fetch_cols(self) -> None:
        self.value = jax.device_get(self.value)

    def event_wait(self) -> None:
        with self._lock:
            self._stop.wait(1.0)
