"""donation-safety bad fixture: read-after-donate and donation of a
persistent cache buffer.  Parsed by the lint, never imported."""
import jax


def patch_rows_donated():
    return jax.jit(
        lambda col, idx, vals: col.at[idx].set(vals),
        donate_argnums=(0,),
    )


def sync(col, idx, vals):
    patch = patch_rows_donated()
    out = patch(col, idx, vals)
    # BAD: `col` was donated above; this read sees freed memory on a
    # real accelerator (CPU silently copies instead)
    return col.sum() + out.sum()


class Worker:
    def __init__(self):
        self._cols = None

    def sync_cached(self, idx, vals):
        patch = patch_rows_donated()
        cols = self._cols
        # BAD: donating a buffer the persistent cache still
        # references — the next sync_cached call reads freed memory
        return patch(cols[0], idx, vals)
