"""donation-safety clean fixture: donated buffers are never read
after the call, and loop-carried names rebind before use."""
import jax


def patch_rows_donated():
    return jax.jit(
        lambda col, idx, vals: col.at[idx].set(vals),
        donate_argnums=(0,),
    )


def sync(col, idx, vals):
    patch = patch_rows_donated()
    out = patch(col, idx, vals)
    return out.sum()


def sync_rebind(buf, idx, vals):
    # the idiomatic donation pattern: the assignment consuming the
    # call rebinds the donated name to the call's output, so every
    # later read (and the next loop iteration) sees the new buffer
    patch = patch_rows_donated()
    for _ in range(3):
        buf = patch(buf, idx, vals)
    return buf.sum()


def sync_many(cols, idx, vals):
    patch = patch_rows_donated()
    patched = []
    # the loop target rebinds `col` at the header each iteration, so
    # the donation inside the body is never followed by a read of
    # the donated buffer
    for col in cols:
        patched.append(patch(col, idx, vals))
    return patched
