"""jit-purity clean fixture: pure jit-reachable code; impure code
exists but is NOT reachable from any jit root."""
import time

import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    return _pure_helper(x) + 1.0


def _pure_helper(x):
    return jnp.maximum(x, 0.0)


def host_timer():
    # impure, but never reachable from a jit decoration: fine
    return time.time()


_probe_kernel = jax.jit(_pure_helper)


def probe(x):
    # module-cached wrapper: no per-call retrace
    return _probe_kernel(x)
