"""jit-purity bad fixture: impure helper reachable from a jit root,
a trace-time global mutation, and a fresh-lambda jit per call."""
import time

import jax


@jax.jit
def kernel(x):
    return _helper(x)


_CALLS = 0


def _helper(x):
    # BAD: runs at trace time only; the timestamp is baked into the
    # compiled executable
    t = time.time()
    global _CALLS
    _CALLS += 1
    return x * t


def probe(x):
    # BAD: a fresh lambda per call gets a fresh jit wrapper — every
    # invocation re-traces and re-compiles
    return jax.jit(lambda a: a + 1)(x)
