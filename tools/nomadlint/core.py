"""nomadlint core: rule base class, findings, suppressions, runner.

A rule is a class with a ``name``, a ``description`` and a
``check(ctx) -> List[Finding]``.  Rules read repo files through a
``Context`` so tests (and the ``check_stage_accounting`` compat shim)
can point individual files at mutated copies without touching the
working tree.

Suppressions are source comments::

    expr_that_trips()  # nomadlint: disable=<rule>[,<rule>...] -- why

or, on their own line, applying to the next line::

    # nomadlint: disable=<rule> -- why
    expr_that_trips()

``disable=all`` suppresses every rule on the line.  The justification
(`` -- why``) is mandatory: a suppression without one is itself
reported (rule ``bare-suppression``) — every deliberate skip must say
why it is safe.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Type

# repo-relative default locations of the files rules inspect; a
# Context override (keyed by the short name) substitutes a copy.
DEFAULT_PATHS: Dict[str, str] = {
    "batch_worker": "nomad_tpu/server/batch_worker.py",
    "plan_apply": "nomad_tpu/server/plan_apply.py",
    "worker": "nomad_tpu/server/worker.py",
    "eval_broker": "nomad_tpu/server/eval_broker.py",
    "api_http": "nomad_tpu/api/http.py",
    "ops_batch": "nomad_tpu/ops/batch.py",
    "ops_solve": "nomad_tpu/ops/solve.py",
    "ops_contracts": "nomad_tpu/ops/contracts.py",
    "trace": "nomad_tpu/trace.py",
    "telemetry": "nomad_tpu/telemetry.py",
    "bench": "bench.py",
    "device_dir": "nomad_tpu/device",
    "device_supervisor": "nomad_tpu/device/supervisor.py",
    "cli": "nomad_tpu/cli.py",
    "explain": "nomad_tpu/explain.py",
    "tpu_stack": "nomad_tpu/sched/tpu_stack.py",
    "feasible": "nomad_tpu/sched/feasible.py",
    "sched_policy": "nomad_tpu/sched/policy.py",
    "sched_storm": "nomad_tpu/sched/storm.py",
    "server": "nomad_tpu/server/server.py",
    "overload": "nomad_tpu/server/overload.py",
    "cluster": "nomad_tpu/server/cluster.py",
    "fanout": "nomad_tpu/server/fanout.py",
    "federation": "nomad_tpu/server/federation.py",
    "envknobs": "nomad_tpu/envknobs.py",
    "decisions": "nomad_tpu/decisions.py",
    "slo": "nomad_tpu/slo.py",
    "arch_doc": "docs/ARCHITECTURE.md",
    "state_dir": "nomad_tpu/state",
    "package": "nomad_tpu",
}

_SUPPRESS_RE = re.compile(
    r"#\s*nomadlint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s+--\s+(\S.*))?\s*$"
)


@dataclass
class Finding:
    rule: str
    path: str  # absolute
    line: int  # 1-based; 0 = whole-file / cross-file finding
    message: str

    def rel(self, repo: str) -> str:
        try:
            return os.path.relpath(self.path, repo)
        except ValueError:
            return self.path

    def to_dict(self, repo: str) -> Dict:
        return {
            "rule": self.rule,
            "path": self.rel(repo),
            "line": self.line,
            "message": self.message,
        }

    def render(self, repo: str) -> str:
        loc = f"{self.rel(repo)}:{self.line}" if self.line else (
            self.rel(repo)
        )
        return f"{loc}: [{self.rule}] {self.message}"


class Context:
    """Resolved file paths + parse caches for one lint run."""

    def __init__(
        self,
        repo: str,
        overrides: Optional[Dict[str, str]] = None,
    ) -> None:
        self.repo = os.path.abspath(repo)
        self.overrides: Dict[str, str] = dict(overrides or {})
        self._trees: Dict[str, ast.AST] = {}
        self._sources: Dict[str, str] = {}

    # -- path resolution ----------------------------------------------

    def default_path(self, key: str) -> str:
        return os.path.join(self.repo, *DEFAULT_PATHS[key].split("/"))

    def path(self, key: str) -> str:
        return self.overrides.get(key, self.default_path(key))

    def scan_files(self, default_key: str = "package") -> List[str]:
        """Python files a repo-wide rule should scan.  A
        ``scan_files`` override (fixture runs) replaces the walk; a
        ``narrow_files`` override (the CLI's ``--files``) restricts
        per-file rules the same way — but cross-file rules
        (``Rule.cross_file``) never see it: the runner hands them a
        de-narrowed context, because a rule that needs both sides of
        a pair (config registry + docs table, a race pair's two
        access sites) would silently false-pass on half its inputs.
        Otherwise the ``default_key`` tree is walked with single-file
        overrides substituted (so a rule pointed at a mutated
        batch_worker copy sees the copy, not the original)."""
        override = self.overrides.get("scan_files")
        if override is None:
            override = self.overrides.get("narrow_files")
        if override is not None:
            return list(override)
        subst = {
            self.default_path(k): v
            for k, v in self.overrides.items()
            if k not in ("scan_files",) and isinstance(v, str)
        }
        out: List[str] = []
        for dirpath, dirnames, filenames in os.walk(
            self.path(default_key)
        ):
            dirnames[:] = [
                d for d in dirnames if d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                out.append(subst.get(p, p))
        return out

    # -- cached IO ----------------------------------------------------

    def source(self, path: str) -> str:
        if path not in self._sources:
            with open(path) as fh:
                self._sources[path] = fh.read()
        return self._sources[path]

    def tree(self, path: str) -> ast.AST:
        if path not in self._trees:
            self._trees[path] = ast.parse(
                self.source(path), filename=path
            )
        return self._trees[path]

    def with_overrides(self, **kw: object) -> "Context":
        merged = dict(self.overrides)
        merged.update(kw)  # type: ignore[arg-type]
        return Context(self.repo, merged)


class Rule:
    """Base class.  Subclasses set ``name``/``description`` and
    implement ``check``; ``bad_fixture`` returns a Context on which
    the rule MUST report at least one finding (the self-test the
    runner's ``--selfcheck`` and tests/test_nomadlint.py exercise).

    ``cross_file = True`` declares that the rule's inputs span files
    (both sides of a registry/doc pair, a race pair's two access
    sites): the runner then ignores CLI ``--files`` narrowing for
    this rule and hands it the full file set, so a narrowed run can
    never false-pass by hiding one side."""

    name: str = ""
    description: str = ""
    cross_file: bool = False

    def check(self, ctx: Context) -> List[Finding]:
        raise NotImplementedError

    @classmethod
    def bad_fixture(cls, ctx: Context, tmpdir: str) -> Context:
        raise NotImplementedError(
            f"rule {cls.name} has no bad fixture"
        )

    @classmethod
    def clean_fixture(cls, ctx: Context, tmpdir: str) -> Context:
        """Context on which the rule must stay quiet.  Defaults to
        the live repo (the repo-wide zero-findings invariant)."""
        return ctx

    # fixture helper: copy the file behind ``key`` into tmpdir with
    # ``old`` replaced by ``new`` (or ``append`` added) and return a
    # Context overriding that key.
    @classmethod
    def _mutated(
        cls,
        ctx: Context,
        tmpdir: str,
        key: str,
        old: str = "",
        new: str = "",
        append: str = "",
    ) -> Context:
        src = ctx.source(ctx.path(key))
        if old:
            assert old in src, (cls.name, key, old)
            src = src.replace(old, new)
        if append:
            src = src + "\n" + append
        out = os.path.join(
            tmpdir, f"{cls.name}_{os.path.basename(ctx.path(key))}"
        )
        with open(out, "w") as fh:
            fh.write(src)
        return ctx.with_overrides(**{key: out})


_REGISTRY: List[Type[Rule]] = []


def register(cls: Type[Rule]) -> Type[Rule]:
    assert cls.name, cls
    assert all(r.name != cls.name for r in _REGISTRY), cls.name
    _REGISTRY.append(cls)
    return cls


def all_rules() -> List[Type[Rule]]:
    # import for side effect: rule modules self-register
    from . import rules  # noqa: F401

    return list(_REGISTRY)


# -- suppressions ------------------------------------------------------


@dataclass
class _Suppression:
    rules: List[str]
    reason: Optional[str]
    line: int  # line the pragma is written on
    applies_to: int  # line findings must be on to match
    used: bool = field(default=False)


def _file_suppressions(source: str) -> List[_Suppression]:
    out: List[_Suppression] = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        names = [
            part.strip()
            for part in m.group(1).split(",")
            if part.strip()
        ]
        standalone = text.lstrip().startswith("#")
        out.append(
            _Suppression(
                rules=names,
                reason=m.group(2),
                line=i,
                applies_to=i + 1 if standalone else i,
            )
        )
    return out


@dataclass
class RunResult:
    findings: List[Finding]
    suppressed: List[Finding]
    rules_run: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings


def run(
    ctx: Context,
    rule_names: Optional[Sequence[str]] = None,
) -> RunResult:
    classes = all_rules()
    if rule_names is not None:
        wanted = set(rule_names)
        unknown = wanted - {c.name for c in classes}
        if unknown:
            raise ValueError(
                f"unknown rule(s): {sorted(unknown)}"
            )
        classes = [c for c in classes if c.name in wanted]
    # cross-file rules ignore CLI --files narrowing: they need both
    # sides of their pairs, so they run against the full file set
    full_ctx = ctx
    if "narrow_files" in ctx.overrides and any(
        c.cross_file for c in classes
    ):
        merged = {
            k: v
            for k, v in ctx.overrides.items()
            if k != "narrow_files"
        }
        full_ctx = Context(ctx.repo, merged)
    findings: List[Finding] = []
    for cls in classes:
        rule_ctx = full_ctx if cls.cross_file else ctx
        findings.extend(cls().check(rule_ctx))

    kept: List[Finding] = []
    suppressed: List[Finding] = []
    cache: Dict[str, List[_Suppression]] = {}
    for f in findings:
        sups = None
        if f.line:
            if f.path not in cache:
                try:
                    cache[f.path] = _file_suppressions(
                        ctx.source(f.path)
                    )
                except OSError:
                    cache[f.path] = []
            sups = [
                s
                for s in cache[f.path]
                if s.applies_to == f.line
                and ("all" in s.rules or f.rule in s.rules)
            ]
        if sups:
            for s in sups:
                s.used = True
            suppressed.append(f)
        else:
            kept.append(f)
    # a suppression without a justification is itself a finding,
    # whether or not it currently hides anything — a bare pragma
    # left behind by a refactor (or typo'd onto the wrong line)
    # would otherwise silently swallow the next finding that lands
    # on it.  Scan the run's file set, not just files with findings.
    for path in set(ctx.scan_files()) | set(cache):
        if path not in cache:
            try:
                cache[path] = _file_suppressions(
                    ctx.source(path)
                )
            except OSError:
                cache[path] = []
        for s in cache[path]:
            if not s.reason:
                kept.append(
                    Finding(
                        rule="bare-suppression",
                        path=path,
                        line=s.line,
                        message=(
                            "suppression without a justification "
                            "(append `-- <one-line reason>`)"
                        ),
                    )
                )
    # a justified suppression that no longer hides anything is dead
    # weight with teeth: the next finding that lands on its line is
    # silently swallowed.  Only a FULL run can tell (a --rules or
    # --files narrowing legitimately skips the rule that would have
    # matched), and suppressions naming unregistered rules are left
    # to the bare/typo case above.
    if rule_names is None and "narrow_files" not in ctx.overrides:
        registered = {c.name for c in classes}
        for path, sups in cache.items():
            for s in sups:
                if (
                    s.reason
                    and not s.used
                    and "all" not in s.rules
                    and set(s.rules) <= registered
                ):
                    kept.append(
                        Finding(
                            rule="stale-suppression",
                            path=path,
                            line=s.line,
                            message=(
                                "suppression for "
                                f"{','.join(s.rules)} hides no "
                                "finding anymore — the code it "
                                "justified changed; remove it so "
                                "it can't swallow the next "
                                "finding on this line"
                            ),
                        )
                    )
    kept.sort(key=lambda f: (f.rule, f.path, f.line))
    return RunResult(
        findings=kept,
        suppressed=suppressed,
        rules_run=[c.name for c in classes],
    )
