"""Shared AST extraction helpers for nomadlint rules.

These started life inside ``tools/check_stage_accounting.py`` (the
608-line monolith the rule suite replaced); the compat shim re-exports
them so the historical helper API keeps working.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

# the trace-recording call surface (nomad_tpu/trace.py Tracer)
TRACE_CALLS = {"span", "add_span", "event"}

# the telemetry emission surface (nomad_tpu/telemetry.py Metrics)
METRIC_CALLS = ("incr", "set_gauge", "add_sample")


def parse(path: str) -> ast.AST:
    with open(path) as fh:
        return ast.parse(fh.read(), filename=path)


def read(path: str) -> str:
    with open(path) as fh:
        return fh.read()


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def timings_keys(tree: ast.AST) -> Set[str]:
    """Keys of the ``self.timings = {...}`` dict literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Attribute)
                and target.attr == "timings"
                and isinstance(node.value, ast.Dict)
            ):
                return {
                    k.value
                    for k in node.value.keys
                    if isinstance(k, ast.Constant)
                }
    return set()


def expr_strings(
    expr: ast.AST, env: Optional[Dict[str, Set[str]]] = None
) -> Set[str]:
    """The string constants an expression may evaluate to: a plain
    constant, BOTH arms of an ``"a" if cond else "b"`` conditional, or
    — given ``env`` from :func:`literal_env` — a Name bound to such an
    expression.  The mesh hot path selects its stage key this way
    (``"mesh_launch" if asm.use_mesh else "launch"``), so key/span
    accounting must see through the conditional."""
    if isinstance(expr, ast.Constant) and isinstance(
        expr.value, str
    ):
        return {expr.value}
    if isinstance(expr, ast.IfExp):
        return expr_strings(expr.body, env) | expr_strings(
            expr.orelse, env
        )
    if env is not None and isinstance(expr, ast.Name):
        return env.get(expr.id, set())
    return set()


def literal_env(tree: ast.AST) -> Dict[str, Set[str]]:
    """name -> possible string values, from every simple
    ``name = <string expr>`` assignment in the module.  Module-wide
    (not scoped): collisions union, which can only over-approximate —
    fine for registry-membership checks."""
    env: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            vals = expr_strings(node.value)
            if vals:
                env.setdefault(
                    node.targets[0].id, set()
                ).update(vals)
    return env


def observed_keys(tree: ast.AST) -> Set[str]:
    """First-arg stage keys of every ``._observe(...)`` call
    (``._observe_chunk`` delegates its stage key to ``_observe``, so
    its call sites count too).  Conditional keys — the mesh path's
    ``"mesh_launch" if ... else "launch"``, possibly bound to a local
    first — contribute both arms."""
    env = literal_env(tree)
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("_observe", "_observe_chunk")
            and node.args
        ):
            out |= expr_strings(node.args[0], env)
    return out


def span_names_used(tree: ast.AST) -> Set[str]:
    """Span/event name literals passed to ``.span/.add_span/.event``
    calls.  The name is the first *string-constant* positional (the
    leading positional is the eval-id expression, never a literal).
    ``._observe_chunk("<stage>", ...)`` emits its span name as
    f"batch_worker.{stage}" — a non-constant the AST scan can't see —
    so its stage keys (including both arms of the mesh path's
    conditional, via :func:`expr_strings`) count as that derived name
    here."""
    env = literal_env(tree)
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr == "_observe_chunk" and node.args:
            stages = expr_strings(node.args[0], env)
            if stages:
                out |= {f"batch_worker.{s}" for s in stages}
                continue
        if node.func.attr not in TRACE_CALLS:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                out.add(arg.value)
                break
    return out


def span_registry(tree: ast.AST) -> Set[str]:
    """String constants inside the ``SPAN_NAMES = frozenset({...})``
    assignment in nomad_tpu/trace.py."""
    return assigned_strings(tree, "SPAN_NAMES")


def assigned_strings(tree: ast.AST, target_name: str) -> Set[str]:
    """String constants reachable inside a module-level assignment to
    ``target_name`` (registries are frozenset/tuple/dict literals —
    collecting every string constant under the value covers all)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Name)
                and target.id == target_name
            ):
                return {
                    n.value
                    for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
    return set()


def dict_key_strings(tree: ast.AST, target_name: str) -> Set[str]:
    """String KEYS of a module-level ``target_name = {...}`` dict
    literal, annotated or not (values — defaults, owners, prose —
    are not keys and must not leak into a registry extraction)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for target in targets:
            if (
                isinstance(target, ast.Name)
                and target.id == target_name
                and isinstance(node.value, ast.Dict)
            ):
                return {
                    k.value
                    for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }
    return set()


def metric_names_emitted(
    tree: ast.AST, prefix: str
) -> Set[str]:
    """Metric-name literals with ``prefix`` emitted anywhere in a
    module: first string-constant positional of ``.incr(...)``,
    ``.set_gauge(...)`` or ``.add_sample(...)`` calls."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in METRIC_CALLS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith(prefix)
        ):
            out.add(node.args[0].value)
    return out


def device_metric_registry(tree: ast.AST) -> Set[str]:
    """String constants inside the ``METRIC_COUNTERS`` /
    ``METRIC_GAUGES`` / ``METRIC_SAMPLES`` frozenset literals in
    device/supervisor.py (the names zero-registered at supervisor
    construction, hence always present in ``prometheus_text()``)."""
    out: Set[str] = set()
    for name in ("METRIC_COUNTERS", "METRIC_GAUGES", "METRIC_SAMPLES"):
        out |= assigned_strings(tree, name)
    return out


def string_constants(
    tree: ast.AST, *, skip_docstrings: bool = True
) -> List[Tuple[str, int]]:
    """All string constants in a module as (value, lineno), optionally
    excluding docstrings (the first statement-expression string of a
    module/class/function body)."""
    doc_nodes: Set[int] = set()
    if skip_docstrings:
        for node in ast.walk(tree):
            if isinstance(
                node,
                (
                    ast.Module,
                    ast.ClassDef,
                    ast.FunctionDef,
                    ast.AsyncFunctionDef,
                ),
            ):
                body = getattr(node, "body", [])
                if (
                    body
                    and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)
                ):
                    doc_nodes.add(id(body[0].value))
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and id(node) not in doc_nodes
        ):
            out.append((node.value, node.lineno))
    return out


def functions_by_name(
    tree: ast.AST,
) -> Dict[str, ast.FunctionDef]:
    """Every (possibly nested) FunctionDef in a module by bare name.
    On name collisions the first definition wins — good enough for the
    module-local call resolution the rules do."""
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            out.setdefault(node.name, node)
    return out
