"""nomadlint: the repo's pluggable AST static-analysis suite.

    python -m tools.nomadlint            # run all rules on the repo
    python -m tools.nomadlint --json     # machine-readable findings
    python -m tools.nomadlint --list-rules
    python -m tools.nomadlint --rules donation-safety,jit-purity
    python -m tools.nomadlint --files path/to/file.py  # narrow scan
    python -m tools.nomadlint --selfcheck  # every rule trips its
                                           # bad fixture

Exit codes: 0 = no unsuppressed findings, 1 = findings, 2 = usage.

The 11 historical stage-accounting checks live here as rules (see
``rules/stage_accounting.py``); ``tools/check_stage_accounting.py``
is a compatibility shim over them.  Four newer passes target the
donated/speculative/multi-threaded hot path: ``donation-safety``,
``jit-purity``, ``lock-discipline`` and ``config-drift``.  See the
"Static analysis" section of docs/ARCHITECTURE.md for the rule
inventory, the suppression syntax and how to add a rule.
"""
from .core import (  # noqa: F401
    Context,
    Finding,
    Rule,
    all_rules,
    register,
    run,
)
