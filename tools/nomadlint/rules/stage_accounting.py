"""The 11 historical stage-accounting checks as individual rules.

These migrated 1:1 from the ``tools/check_stage_accounting.py``
monolith (which now shims onto them); the check numbers in each
docstring refer to that file's original numbering, and the messages
keep the original wording so operator muscle memory (and the tier-1
test's substring asserts) survive the migration.
"""
from __future__ import annotations

import ast
import os
from typing import List, Set

from .. import astutil
from ..core import Context, Finding, Rule, register

# allocs_fit / BinPackIterator exhaustion-dimension vocabulary a
# literal exhausted_node() in the vectorized path may use
EXHAUST_DIMENSIONS = {"cpu", "memory", "disk"}


def _device_module_paths(ctx: Context) -> List[str]:
    device_dir = ctx.path("device_dir")
    subst = {}
    sup = ctx.overrides.get("device_supervisor")
    if sup:
        subst[ctx.default_path("device_supervisor")] = sup
    return sorted(
        subst.get(
            os.path.join(device_dir, name),
            os.path.join(device_dir, name),
        )
        for name in os.listdir(device_dir)
        if name.endswith(".py")
    )


@register
class StageObservedRule(Rule):
    """Check 1: every key in the ``self.timings = {...}`` literal in
    batch_worker.py appears in at least one ``self._observe(...)``
    call — a stage added without observation would stay 0 forever."""

    name = "stage-observed"
    description = (
        "every BatchWorker.timings key is observed via _observe"
    )

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("batch_worker")
        tree = ctx.tree(path)
        declared = astutil.timings_keys(tree)
        if not declared:
            return [
                Finding(
                    self.name, path, 0,
                    "could not find the self.timings literal in "
                    "batch_worker.py",
                )
            ]
        unobserved = declared - astutil.observed_keys(tree)
        if unobserved:
            return [
                Finding(
                    self.name, path, 0,
                    "timings keys never passed to _observe (stage "
                    "time would stay 0 forever): "
                    f"{sorted(unobserved)}",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            old='self._observe("simulate"',
            new='_unused("simulate"',
        )


@register
class StageOrphansRule(Rule):
    """Check 2: every ``self._observe("<key>", ...)`` call uses a
    declared timings key (no orphan stages accumulating into
    nothing)."""

    name = "stage-orphans"
    description = "every _observe key is declared in timings"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("batch_worker")
        tree = ctx.tree(path)
        declared = astutil.timings_keys(tree)
        if not declared:
            # stage-observed already reports the missing literal
            return []
        orphans = astutil.observed_keys(tree) - declared
        if orphans:
            return [
                Finding(
                    self.name, path, 0,
                    "_observe calls with keys missing from the "
                    "timings literal (would KeyError at runtime): "
                    f"{sorted(orphans)}",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            old='self._observe("simulate"',
            new='self._observe("bogus_simulate"',
        )


@register
class BenchStageExportRule(Rule):
    """Check 3: bench.py snapshots ``worker.timings`` wholesale
    (``dict(worker.timings)``) and exports ``e2e_stage_times_s``, so
    new stages flow into BENCH_*.json without a bench edit."""

    name = "bench-stage-export"
    description = "bench.py exports the stage timings wholesale"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("bench")
        tree = ctx.tree(path)
        source = ctx.source(path)
        out: List[Finding] = []
        wholesale = any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "dict"
            and node.args
            and isinstance(node.args[0], ast.Attribute)
            and node.args[0].attr == "timings"
            for node in ast.walk(tree)
        )
        if not wholesale:
            out.append(
                Finding(
                    self.name, path, 0,
                    "bench.py no longer snapshots the stage times "
                    "wholesale (expected a dict(worker.timings) "
                    "call) — new stages would silently drop from "
                    "the bench",
                )
            )
        if '"e2e_stage_times_s"' not in source:
            out.append(
                Finding(
                    self.name, path, 0,
                    "bench.py no longer exports the "
                    "e2e_stage_times_s JSON key",
                )
            )
        return out

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "bench",
            old='"e2e_stage_times_s"',
            new='"renamed_stage_times_s"',
        )


@register
class SpanRegistryRule(Rule):
    """Checks 4+5 (span half), generalized: every span/event name
    literal used with ``TRACE.span/add_span/event`` anywhere in
    ``nomad_tpu/`` must be declared in the ``SPAN_NAMES`` registry in
    trace.py — a renamed stage must update the documented registry
    (and with it every dashboard/report keyed on the name), never
    drift silently.  Check 10's span half rides along: the
    continuous-micro-batching admission names must stay registered
    even if their call sites change shape."""

    name = "span-registry"
    description = "every span/event literal is in trace.SPAN_NAMES"

    REQUIRED = (
        "batch_worker.admit",
        "batch_worker.admit_deferred",
        # follower scheduling fan-out: the lease RPC on every
        # remotely dequeued eval and the serialized-commit round
        # trip into the leader's plan queue — without them a
        # follower-planned eval's trace loses its cross-server hops
        "fanout.remote_dequeue",
        "fanout.plan_submit",
        # cluster-scope observability: the follower's segment-ship
        # marker (the stitched waterfall's cross-server seam) and
        # the leader's fan-in query span — without them a stitched
        # trace can't show WHEN spans left the follower, and a slow
        # /v1/cluster/* query has no flight-recorder trail
        "fanout.remote_span_ship",
        "cluster.fanin",
        # the overload control plane's incident roots: the per-
        # excursion shed incident and the batched mass node-death
        # wave — without them an overload or a rack death leaves no
        # flight-recorder trail
        "ingress.shed",
        "server.node_down_wave",
        # the sharded hot path's pipeline stages: mesh time must stay
        # separable from single-chip chunk time on every dashboard
        "batch_worker.mesh_launch",
        "batch_worker.mesh_fetch",
        # the global storm solver's lifecycle: the coalesced drain,
        # the single device solve, the per-eval decomposition, and
        # every serial-chain fallback — the auditability half of the
        # relaxed serial-equivalence contract
        "batch_worker.storm_gulp",
        "batch_worker.storm_solve",
        "batch_worker.storm_decompose",
        "storm.fallback",
        # policy-weighted scoring: the per-member weight-tensor
        # assembly inside storm staging — without it a weighted
        # storm's staging cost is invisible on every trace dashboard
        "batch_worker.policy_assemble",
        # multi-region federation: the cross-region forward and the
        # Multiregion fan-out roots — without them a WAN hop leaves
        # no flight-recorder trail and a fanned job's per-region
        # registrations can't be attributed
        "federation.forward",
        "federation.fanout",
    )

    def check(self, ctx: Context) -> List[Finding]:
        trace_path = ctx.path("trace")
        registry = astutil.span_registry(ctx.tree(trace_path))
        if not registry:
            return [
                Finding(
                    self.name, trace_path, 0,
                    "could not find the SPAN_NAMES registry in "
                    "nomad_tpu/trace.py",
                )
            ]
        out: List[Finding] = []
        trace_default = ctx.default_path("trace")
        for path in ctx.scan_files():
            # trace.py is the registry itself (its internal add_span
            # plumbing passes variables, not stage literals)
            if path in (trace_path, trace_default):
                continue
            used = astutil.span_names_used(ctx.tree(path))
            unregistered = used - registry
            if unregistered:
                out.append(
                    Finding(
                        self.name, path, 0,
                        "span names used but missing from "
                        "trace.SPAN_NAMES (rename must update the "
                        "documented registry): "
                        f"{sorted(unregistered)}",
                    )
                )
        for required in self.REQUIRED:
            if required not in registry:
                out.append(
                    Finding(
                        self.name, trace_path, 0,
                        f"{required!r} missing from "
                        "trace.SPAN_NAMES — a required pipeline "
                        "stage would vanish from every trace-keyed "
                        "dashboard",
                    )
                )
        return out

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "trace",
            old='"batch_worker.simulate"',
            new='"batch_worker.renamed_simulate"',
        )


@register
class DeviceMetricsRule(Rule):
    """Check 5 (metric half): every ``device.*`` counter/gauge/sample
    emitted by the accelerator supervisor modules appears in the
    ``METRIC_COUNTERS``/``METRIC_GAUGES``/``METRIC_SAMPLES`` registry
    literals in device/supervisor.py — those are zero-registered at
    supervisor construction, which is what guarantees
    ``prometheus_text()`` exports the whole family before the first
    incident."""

    name = "device-metrics"
    description = "device.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        sup_path = ctx.path("device_supervisor")
        registry = astutil.device_metric_registry(
            ctx.tree(sup_path)
        )
        if not registry:
            return [
                Finding(
                    self.name, sup_path, 0,
                    "could not find the METRIC_COUNTERS/GAUGES/"
                    "SAMPLES registry in device/supervisor.py",
                )
            ]
        emitted: Set[str] = set()
        for path in _device_module_paths(ctx):
            emitted |= astutil.metric_names_emitted(
                ctx.tree(path), "device."
            )
        unexported = emitted - registry
        if unexported:
            return [
                Finding(
                    self.name, sup_path, 0,
                    "device.* metrics emitted but not in the "
                    "supervisor's zero-registered registry (they "
                    "would be absent from prometheus_text() until "
                    f"the first incident): {sorted(unexported)}",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "device_supervisor",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.incr("device.bogus_metric")\n'
            ),
        )


@register
class DebugBundleDeviceRule(Rule):
    """Check 6: the operator debug bundle (cli.py
    ``cmd_operator_debug``) captures ``/v1/device``, so a bundle from
    a degraded server always carries the supervisor's state
    history."""

    name = "debug-bundle-device"
    description = "operator debug bundle captures /v1/device"

    # quoted form: "/v1/devices" (the fingerprint family) must not
    # satisfy the supervisor-status capture check
    NEEDLE = '"/v1/device"'
    ENDPOINT = "/v1/device"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("cli")
        bundle_src = ctx.source(path).split(
            "cmd_operator_debug", 1
        )[-1].split("def ", 1)[0]
        if self.NEEDLE not in bundle_src:
            return [
                Finding(
                    self.name, path, 0,
                    "the operator debug bundle "
                    "(cli.cmd_operator_debug) no longer captures "
                    f"{self.ENDPOINT}",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        # drop the last path char: the mutated source must not keep
        # the needle as a substring ("/v1/placements_renamed" would)
        return cls._mutated(
            ctx, tmpdir, "cli",
            old=cls.ENDPOINT,
            new=cls.ENDPOINT[:-1],
        )


@register
class DebugBundlePlacementsRule(DebugBundleDeviceRule):
    """Check 9: the operator debug bundle captures
    ``/v1/placements`` so the per-eval explanations travel with the
    traces they cross-reference."""

    name = "debug-bundle-placements"
    description = "operator debug bundle captures /v1/placements"

    NEEDLE = "/v1/placements"
    ENDPOINT = "/v1/placements"


@register
class PlacementMetricsRule(Rule):
    """Check 7: placement.* emissions in explain.py stay inside the
    zero-registered families.  Literal names must be registered
    verbatim; f-string names may only be `placement.filtered.{...}` /
    `placement.exhausted.{...}` with the slug produced by
    reason_slug()/dimension_slug() (the fixed vocabularies); and the
    server zero-registers the family at construction."""

    name = "placement-metrics"
    description = "placement.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("explain")
        tree = ctx.tree(path)
        problems: List[Finding] = []
        counters = astutil.assigned_strings(
            tree, "PLACEMENT_COUNTERS"
        )
        gauges = astutil.assigned_strings(tree, "PLACEMENT_GAUGES")
        filter_slugs = astutil.assigned_strings(
            tree, "PLACEMENT_FILTER_SLUGS"
        )
        exhaust_slugs = astutil.assigned_strings(
            tree, "PLACEMENT_EXHAUST_SLUGS"
        )
        if not (
            counters and gauges and filter_slugs and exhaust_slugs
        ):
            return [
                Finding(
                    self.name, path, 0,
                    "could not find the PLACEMENT_* registries in "
                    "nomad_tpu/explain.py",
                )
            ]
        registered = (
            counters
            | gauges
            | {f"placement.filtered.{s}" for s in filter_slugs}
            | {f"placement.exhausted.{s}" for s in exhaust_slugs}
        )
        slug_fns = {"reason_slug", "dimension_slug"}
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in astutil.METRIC_CALLS
                and node.args
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                if arg.value.startswith("placement.") and (
                    arg.value not in registered
                ):
                    problems.append(
                        Finding(
                            self.name, path, node.lineno,
                            f"placement metric {arg.value!r} "
                            "emitted but not in the "
                            "zero-registered PLACEMENT_* "
                            "registries",
                        )
                    )
                continue
            if isinstance(arg, ast.JoinedStr):
                prefix = ""
                if arg.values and isinstance(
                    arg.values[0], ast.Constant
                ):
                    prefix = str(arg.values[0].value)
                if not prefix.startswith("placement."):
                    continue
                if prefix not in (
                    "placement.filtered.",
                    "placement.exhausted.",
                ):
                    problems.append(
                        Finding(
                            self.name, path, node.lineno,
                            "dynamic placement metric prefix "
                            f"{prefix!r} has no zero-registered "
                            "family",
                        )
                    )
                    continue
                for part in arg.values[1:]:
                    if not isinstance(part, ast.FormattedValue):
                        continue
                    call = part.value
                    ok = (
                        isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Name)
                        and call.func.id in slug_fns
                    )
                    if not ok:
                        problems.append(
                            Finding(
                                self.name, path, node.lineno,
                                "placement metric family "
                                f"{prefix!r} interpolates a value "
                                "not produced by reason_slug()/"
                                "dimension_slug() — the name space "
                                "would be unbounded",
                            )
                        )
        server_path = ctx.path("server")
        server_src = ctx.source(server_path)
        if (
            "preregister" not in server_src
            or "explain" not in server_src
        ):
            problems.append(
                Finding(
                    self.name, server_path, 0,
                    "server.py no longer zero-registers the "
                    "placement.* families at construction "
                    "(explain.preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "explain",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.incr("placement.bogus_metric")\n'
            ),
        )


@register
class ReasonVocabularyRule(Rule):
    """Check 8: reason-string literals used by the vectorized path
    must come from the serial chain's shared vocabulary — a string
    literal passed to ``filter_node(...)`` in sched/tpu_stack.py must
    be one of the ``FILTER_*`` constants' values (sched/feasible.py),
    and a literal ``exhausted_node(...)`` dimension must be in the
    ``allocs_fit`` superset vocabulary."""

    name = "reason-vocab"
    description = "vectorized-path reason literals use shared vocab"

    def check(self, ctx: Context) -> List[Finding]:
        feasible_path = ctx.path("feasible")
        allowed: Set[str] = set()
        for node in ast.walk(ctx.tree(feasible_path)):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id.startswith("FILTER_")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    allowed.add(node.value.value)
        if not allowed:
            return [
                Finding(
                    self.name, feasible_path, 0,
                    "could not find the FILTER_* reason constants "
                    "in sched/feasible.py",
                )
            ]
        stack_path = ctx.path("tpu_stack")
        problems: List[Finding] = []
        for node in ast.walk(ctx.tree(stack_path)):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                continue
            literal = node.args[1].value
            if (
                node.func.attr == "filter_node"
                and literal not in allowed
            ):
                problems.append(
                    Finding(
                        self.name, stack_path, node.lineno,
                        "ad-hoc filter reason literal in "
                        f"sched/tpu_stack.py: {literal!r} is not a "
                        "shared FILTER_* constant value (import "
                        "the constant instead)",
                    )
                )
            if (
                node.func.attr == "exhausted_node"
                and literal not in EXHAUST_DIMENSIONS
            ):
                problems.append(
                    Finding(
                        self.name, stack_path, node.lineno,
                        "ad-hoc exhaustion dimension literal in "
                        f"sched/tpu_stack.py: {literal!r} is "
                        "outside the allocs_fit superset "
                        "vocabulary",
                    )
                )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "tpu_stack",
            append=(
                "def _nomadlint_bad_fixture(it, node):\n"
                '    it.filter_node(node, "bogus ad-hoc reason")\n'
            ),
        )


@register
class AdmissionMetricsRule(Rule):
    """Check 10 (counter half): every ``admission.*`` metric the
    batch worker emits — literal first args of metric calls plus the
    ``self._count_admission("<kind>")`` sites, which emit
    ``admission.<kind>`` — is in the zero-registered
    ``ADMISSION_COUNTERS`` registry, and server.py actually
    zero-registers it."""

    name = "admission-metrics"
    description = "admission.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("batch_worker")
        tree = ctx.tree(path)
        registry = astutil.assigned_strings(
            tree, "ADMISSION_COUNTERS"
        )
        if not registry:
            return [
                Finding(
                    self.name, path, 0,
                    "could not find the ADMISSION_COUNTERS "
                    "registry in batch_worker.py",
                )
            ]
        emitted: Set[str] = set()
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            if (
                node.func.attr in astutil.METRIC_CALLS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("admission.")
            ):
                emitted.add(node.args[0].value)
            if (
                node.func.attr == "_count_admission"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                emitted.add(f"admission.{node.args[0].value}")
        problems: List[Finding] = []
        unregistered = emitted - registry
        if unregistered:
            problems.append(
                Finding(
                    self.name, path, 0,
                    "admission.* metrics emitted but not in the "
                    "ADMISSION_COUNTERS registry (they would be "
                    "absent from prometheus scrapes until the "
                    "first mid-chain admission): "
                    f"{sorted(unregistered)}",
                )
            )
        server_path = ctx.path("server")
        if "ADMISSION_COUNTERS" not in ctx.source(server_path):
            problems.append(
                Finding(
                    self.name, server_path, 0,
                    "server.py no longer zero-registers the "
                    "admission.* family at construction "
                    "(ADMISSION_COUNTERS preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.incr("admission.bogus_metric")\n'
            ),
        )


@register
class LatencySweepRule(Rule):
    """Check 11: bench.py exports the ``latency_sweep`` JSON block
    (offered-load vs p50/p99 with p99 trace exemplars) — the
    per-round tracking of the <250 ms tail-latency target."""

    name = "latency-sweep"
    description = "bench.py exports the latency_sweep block"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("bench")
        if '"latency_sweep"' not in ctx.source(path):
            return [
                Finding(
                    self.name, path, 0,
                    "bench.py no longer exports the latency_sweep "
                    "JSON block (offered-load vs p50/p99 with p99 "
                    "trace exemplars)",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "bench",
            old='"latency_sweep"',
            new='"renamed_latency_sweep"',
        )


@register
class MeshMetricsRule(Rule):
    """Sharded hot path: every ``mesh.*`` counter/gauge the batch
    worker emits is in the zero-registered ``MESH_COUNTERS`` /
    ``MESH_GAUGES`` registries, and server.py zero-registers both at
    construction — absence of a ``mesh.*`` series must mean "mesh
    never engaged", never "not exported"."""

    name = "mesh-metrics"
    description = "mesh.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("batch_worker")
        tree = ctx.tree(path)
        registry = astutil.assigned_strings(
            tree, "MESH_COUNTERS"
        ) | astutil.assigned_strings(tree, "MESH_GAUGES")
        if not registry:
            return [
                Finding(
                    self.name, path, 0,
                    "could not find the MESH_COUNTERS/MESH_GAUGES "
                    "registries in batch_worker.py",
                )
            ]
        emitted = astutil.metric_names_emitted(tree, "mesh.")
        problems: List[Finding] = []
        unregistered = emitted - registry
        if unregistered:
            problems.append(
                Finding(
                    self.name, path, 0,
                    "mesh.* metrics emitted but not in the "
                    "MESH_COUNTERS/MESH_GAUGES registries (they "
                    "would be absent from prometheus scrapes until "
                    "the first sharded flush): "
                    f"{sorted(unregistered)}",
                )
            )
        server_path = ctx.path("server")
        server_src = ctx.source(server_path)
        for reg_name in ("MESH_COUNTERS", "MESH_GAUGES"):
            if reg_name not in server_src:
                problems.append(
                    Finding(
                        self.name, server_path, 0,
                        "server.py no longer zero-registers the "
                        f"mesh.* family at construction ({reg_name} "
                        "preregister)",
                    )
                )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.set_gauge("mesh.bogus_metric", 1.0)\n'
            ),
        )


@register
class StormMetricsRule(Rule):
    """Global storm solver: every ``storm.*`` metric the batch worker
    emits — literal first args of metric calls plus the
    ``self._count_storm("<kind>")`` sites, which emit
    ``storm.<kind>`` — is in the zero-registered ``STORM_COUNTERS`` /
    ``STORM_GAUGES`` registries, and server.py zero-registers both at
    construction: absence of a ``storm.*`` series must mean "no storm
    ever coalesced", never "not exported"."""

    name = "storm-metrics"
    description = "storm.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("batch_worker")
        tree = ctx.tree(path)
        registry = astutil.assigned_strings(
            tree, "STORM_COUNTERS"
        ) | astutil.assigned_strings(tree, "STORM_GAUGES")
        if not registry:
            return [
                Finding(
                    self.name, path, 0,
                    "could not find the STORM_COUNTERS/STORM_GAUGES "
                    "registries in batch_worker.py",
                )
            ]
        emitted: Set[str] = set()
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            if (
                node.func.attr in astutil.METRIC_CALLS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("storm.")
            ):
                emitted.add(node.args[0].value)
            if (
                node.func.attr == "_count_storm"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                emitted.add(f"storm.{node.args[0].value}")
        problems: List[Finding] = []
        unregistered = emitted - registry
        if unregistered:
            problems.append(
                Finding(
                    self.name, path, 0,
                    "storm.* metrics emitted but not in the "
                    "STORM_COUNTERS/STORM_GAUGES registries (they "
                    "would be absent from prometheus scrapes until "
                    "the first coalesced solve): "
                    f"{sorted(unregistered)}",
                )
            )
        server_path = ctx.path("server")
        server_src = ctx.source(server_path)
        for reg_name in ("STORM_COUNTERS", "STORM_GAUGES"):
            if reg_name not in server_src:
                problems.append(
                    Finding(
                        self.name, server_path, 0,
                        "server.py no longer zero-registers the "
                        f"storm.* family at construction ({reg_name} "
                        "preregister)",
                    )
                )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            append=(
                "def _nomadlint_bad_fixture(self):\n"
                '    self._count_storm("bogus_kind")\n'
            ),
        )


@register
class PolicyMetricsRule(Rule):
    """Policy-weighted scoring: every ``policy.*`` metric emitted
    anywhere in the layer — literal first args of metric calls in
    sched/policy.py (tensor-cache accounting), sched/storm.py
    (weighted staging), batch_worker.py and tpu_stack.py, plus the
    ``self._count_policy("<kind>")`` sites, which emit
    ``policy.<kind>`` — is in the zero-registered ``POLICY_COUNTERS``
    / ``POLICY_GAUGES`` registries (sched/policy.py), and server.py
    zero-registers both at construction: absence of a ``policy.*``
    series must mean "no policy-weighted select ever ran", never
    "not exported"."""

    name = "policy-metrics"
    description = "policy.* emissions are zero-registered"

    SCAN_KEYS = (
        "sched_policy", "sched_storm", "batch_worker", "tpu_stack"
    )

    def check(self, ctx: Context) -> List[Finding]:
        policy_path = ctx.path("sched_policy")
        registry = astutil.assigned_strings(
            ctx.tree(policy_path), "POLICY_COUNTERS"
        ) | astutil.assigned_strings(
            ctx.tree(policy_path), "POLICY_GAUGES"
        )
        if not registry:
            return [
                Finding(
                    self.name, policy_path, 0,
                    "could not find the POLICY_COUNTERS/"
                    "POLICY_GAUGES registries in sched/policy.py",
                )
            ]
        problems: List[Finding] = []
        for key in self.SCAN_KEYS:
            path = ctx.path(key)
            tree = ctx.tree(path)
            emitted: Set[str] = set()
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                if (
                    node.func.attr in astutil.METRIC_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("policy.")
                ):
                    emitted.add(node.args[0].value)
                if (
                    node.func.attr == "_count_policy"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    emitted.add(f"policy.{node.args[0].value}")
            unregistered = emitted - registry
            if unregistered:
                problems.append(
                    Finding(
                        self.name, path, 0,
                        "policy.* metrics emitted but not in the "
                        "POLICY_COUNTERS/POLICY_GAUGES registries "
                        "(they would be absent from prometheus "
                        "scrapes until the first weighted select): "
                        f"{sorted(unregistered)}",
                    )
                )
        server_path = ctx.path("server")
        server_src = ctx.source(server_path)
        for reg_name in ("POLICY_COUNTERS", "POLICY_GAUGES"):
            if reg_name not in server_src:
                problems.append(
                    Finding(
                        self.name, server_path, 0,
                        "server.py no longer zero-registers the "
                        f"policy.* family at construction ({reg_name}"
                        " preregister)",
                    )
                )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            append=(
                "def _nomadlint_bad_fixture(self):\n"
                '    self._count_policy("bogus_kind")\n'
            ),
        )


@register
class LeadershipMetricsRule(Rule):
    """Leadership failover: every ``leadership.*`` / ``raft.*`` metric
    emitted by server.py, batch_worker.py, plan_apply.py or
    cluster.py — literal first args of metric calls plus the
    ``self._count_leadership("<kind>")`` sites, which emit
    ``leadership.<kind>`` — is in the zero-registered
    ``LEADERSHIP_COUNTERS`` / ``LEADERSHIP_GAUGES`` registries
    (server.py) and server.py preregisters them at construction:
    absence of a ``leadership.*`` series must mean "leadership never
    changed", never "not exported"."""

    name = "leadership-metrics"
    description = "leadership.*/raft.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        server_path = ctx.path("server")
        registry = astutil.assigned_strings(
            ctx.tree(server_path), "LEADERSHIP_COUNTERS"
        ) | astutil.assigned_strings(
            ctx.tree(server_path), "LEADERSHIP_GAUGES"
        )
        if not registry:
            return [
                Finding(
                    self.name, server_path, 0,
                    "could not find the LEADERSHIP_COUNTERS/"
                    "LEADERSHIP_GAUGES registries in server.py",
                )
            ]
        problems: List[Finding] = []
        for key in ("server", "batch_worker", "plan_apply", "cluster"):
            path = ctx.path(key)
            tree = ctx.tree(path)
            emitted: Set[str] = set()
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                if (
                    node.func.attr in astutil.METRIC_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith(
                        ("leadership.", "raft.")
                    )
                ):
                    emitted.add(node.args[0].value)
                if (
                    node.func.attr == "_count_leadership"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    emitted.add(f"leadership.{node.args[0].value}")
            unregistered = emitted - registry
            if unregistered:
                problems.append(
                    Finding(
                        self.name, path, 0,
                        "leadership./raft. metrics emitted but not "
                        "in the LEADERSHIP_COUNTERS/LEADERSHIP_GAUGES "
                        "registries (they would be absent from "
                        "prometheus scrapes until the first "
                        f"failover): {sorted(unregistered)}",
                    )
                )
        server_src = ctx.source(server_path)
        # the registry assignment is one occurrence; a preregister
        # call site must reference the name at least once more
        if server_src.count("LEADERSHIP_COUNTERS") < 2:
            problems.append(
                Finding(
                    self.name, server_path, 0,
                    "server.py no longer zero-registers the "
                    "leadership.* family at construction "
                    "(LEADERSHIP_COUNTERS preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            append=(
                "def _nomadlint_bad_fixture(self):\n"
                '    self._count_leadership("bogus_kind")\n'
            ),
        )


@register
class OverloadMetricsRule(Rule):
    """Overload control plane: every ``overload.*`` metric emitted by
    overload.py, server.py or api/http.py — literal first args of
    metric calls — is in the zero-registered ``OVERLOAD_COUNTERS`` /
    ``OVERLOAD_GAUGES`` registries (overload.py) and server.py
    preregisters both at construction: absence of an ``overload.*``
    series must mean "never overloaded", never "not exported"."""

    name = "overload-metrics"
    description = "overload.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        overload_path = ctx.path("overload")
        registry = astutil.assigned_strings(
            ctx.tree(overload_path), "OVERLOAD_COUNTERS"
        ) | astutil.assigned_strings(
            ctx.tree(overload_path), "OVERLOAD_GAUGES"
        )
        if not registry:
            return [
                Finding(
                    self.name, overload_path, 0,
                    "could not find the OVERLOAD_COUNTERS/"
                    "OVERLOAD_GAUGES registries in overload.py",
                )
            ]
        problems: List[Finding] = []
        for key in ("overload", "server", "api_http"):
            path = ctx.path(key)
            tree = ctx.tree(path)
            emitted: Set[str] = set()
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                if (
                    node.func.attr in astutil.METRIC_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("overload.")
                ):
                    emitted.add(node.args[0].value)
            unregistered = emitted - registry
            if unregistered:
                problems.append(
                    Finding(
                        self.name, path, 0,
                        "overload.* metrics emitted but not in the "
                        "OVERLOAD_COUNTERS/OVERLOAD_GAUGES "
                        "registries (they would be absent from "
                        "prometheus scrapes until the first "
                        f"overload): {sorted(unregistered)}",
                    )
                )
        server_src = ctx.source(ctx.path("server"))
        if "OVERLOAD_COUNTERS" not in server_src:
            problems.append(
                Finding(
                    self.name, ctx.path("server"), 0,
                    "server.py no longer zero-registers the "
                    "overload.* family at construction "
                    "(OVERLOAD_COUNTERS preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "overload",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.incr("overload.bogus_metric")\n'
            ),
        )


@register
class FanoutMetricsRule(Rule):
    """Follower scheduling fan-out: every ``fanout.*`` metric emitted
    by fanout.py, cluster.py or server.py — literal first args of
    metric calls, the ``self._count_fanout("<kind>")`` worker sites
    and the ``self._count("<kind>")`` RemoteBrokerClient sites (both
    emit ``fanout.<kind>``) — is in the zero-registered
    ``FANOUT_COUNTERS`` / ``FANOUT_GAUGES`` registries (fanout.py)
    and server.py preregisters both at construction: absence of a
    ``fanout.*`` series must mean "fan-out never engaged", never
    "not exported"."""

    name = "fanout-metrics"
    description = "fanout.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        fanout_path = ctx.path("fanout")
        registry = astutil.assigned_strings(
            ctx.tree(fanout_path), "FANOUT_COUNTERS"
        ) | astutil.assigned_strings(
            ctx.tree(fanout_path), "FANOUT_GAUGES"
        )
        if not registry:
            return [
                Finding(
                    self.name, fanout_path, 0,
                    "could not find the FANOUT_COUNTERS/"
                    "FANOUT_GAUGES registries in fanout.py",
                )
            ]
        problems: List[Finding] = []
        for key in ("fanout", "cluster", "server"):
            path = ctx.path(key)
            tree = ctx.tree(path)
            emitted: Set[str] = set()
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                if (
                    node.func.attr in astutil.METRIC_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("fanout.")
                ):
                    emitted.add(node.args[0].value)
                if (
                    key == "fanout"
                    and node.func.attr
                    in ("_count_fanout", "_count")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    emitted.add(f"fanout.{node.args[0].value}")
            unregistered = emitted - registry
            if unregistered:
                problems.append(
                    Finding(
                        self.name, path, 0,
                        "fanout.* metrics emitted but not in the "
                        "FANOUT_COUNTERS/FANOUT_GAUGES registries "
                        "(they would be absent from prometheus "
                        "scrapes until the first remote lease): "
                        f"{sorted(unregistered)}",
                    )
                )
        server_src = ctx.source(ctx.path("server"))
        if "FANOUT_COUNTERS" not in server_src:
            problems.append(
                Finding(
                    self.name, ctx.path("server"), 0,
                    "server.py no longer zero-registers the "
                    "fanout.* family at construction "
                    "(FANOUT_COUNTERS preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "fanout",
            append=(
                "def _nomadlint_bad_fixture(self):\n"
                '    self._count_fanout("bogus_kind")\n'
            ),
        )


@register
class ClusterObsMetricsRule(Rule):
    """Cluster-scope observability plane: every ``cluster.*`` /
    ``obs.*`` metric emitted by telemetry.py, cluster.py, fanout.py,
    server.py or api/http.py — literal first args of metric calls —
    is in the zero-registered ``CLUSTER_OBS_COUNTERS`` /
    ``CLUSTER_OBS_GAUGES`` registries (telemetry.py) and server.py
    preregisters both at construction: absence of a
    ``cluster.fanin_queries`` or ``obs.history_snapshots`` series
    must mean "nothing happened", never "not exported"."""

    name = "cluster-obs-metrics"
    description = "cluster.*/obs.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        telemetry_path = ctx.path("telemetry")
        registry = astutil.assigned_strings(
            ctx.tree(telemetry_path), "CLUSTER_OBS_COUNTERS"
        ) | astutil.assigned_strings(
            ctx.tree(telemetry_path), "CLUSTER_OBS_GAUGES"
        )
        if not registry:
            return [
                Finding(
                    self.name, telemetry_path, 0,
                    "could not find the CLUSTER_OBS_COUNTERS/"
                    "CLUSTER_OBS_GAUGES registries in telemetry.py",
                )
            ]
        problems: List[Finding] = []
        for key in (
            "telemetry", "cluster", "fanout", "server", "api_http",
        ):
            path = ctx.path(key)
            emitted: Set[str] = set()
            for node in ast.walk(ctx.tree(path)):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in astutil.METRIC_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith(
                        ("cluster.", "obs.")
                    )
                ):
                    emitted.add(node.args[0].value)
            unregistered = emitted - registry
            if unregistered:
                problems.append(
                    Finding(
                        self.name, path, 0,
                        "cluster.*/obs.* metrics emitted but not in "
                        "the CLUSTER_OBS_COUNTERS/CLUSTER_OBS_GAUGES "
                        "registries (they would be absent from "
                        "prometheus scrapes until the first fan-in "
                        "query or history snapshot): "
                        f"{sorted(unregistered)}",
                    )
                )
        server_src = ctx.source(ctx.path("server"))
        if "CLUSTER_OBS_COUNTERS" not in server_src:
            problems.append(
                Finding(
                    self.name, ctx.path("server"), 0,
                    "server.py no longer zero-registers the "
                    "cluster.*/obs.* families at construction "
                    "(CLUSTER_OBS_COUNTERS preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "cluster",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.incr("cluster.bogus_metric")\n'
            ),
        )


@register
class ClusterFanoutExportRule(Rule):
    """Follower fan-out: bench.py exports the ``cluster_fanout`` JSON
    block (placements/s through 1/3/5-server clusters with the 3v1
    speedup and zero-lost/parity verdicts) — the per-round proof that
    scheduling throughput actually scales with servers."""

    name = "cluster-fanout-export"
    description = "bench.py exports the cluster_fanout block"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("bench")
        if '"cluster_fanout"' not in ctx.source(path):
            return [
                Finding(
                    self.name, path, 0,
                    "bench.py no longer exports the cluster_fanout "
                    "JSON block (1/3/5-server scheduling-throughput "
                    "scaling with zero-lost/parity verdicts)",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "bench",
            old='"cluster_fanout"',
            new='"renamed_cluster_fanout"',
        )


@register
class SwarmExportRule(Rule):
    """Swarm harness: bench.py exports the ``swarm`` JSON block (the
    SLO-gated overload + mass-death run: heartbeat success, sheds,
    storm-solve count, p99 exemplars) — the per-round proof that the
    control plane degrades instead of collapsing."""

    name = "swarm-export"
    description = "bench.py exports the swarm block"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("bench")
        if '"swarm"' not in ctx.source(path):
            return [
                Finding(
                    self.name, path, 0,
                    "bench.py no longer exports the swarm JSON "
                    "block (SLO-gated overload + mass node-death "
                    "harness results)",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "bench",
            old='"swarm"',
            new='"renamed_swarm"',
        )


@register
class MultichipExportRule(Rule):
    """Sharded hot path: bench.py exports the ``multichip`` JSON block
    (placements/s, host->device bytes/flush, per-device FLOPs vs
    device count) — the per-round proof that the node-sharded pipeline
    actually scales, feeding the MULTICHIP_r*.json tail."""

    name = "multichip-export"
    description = "bench.py exports the multichip block"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("bench")
        if '"multichip"' not in ctx.source(path):
            return [
                Finding(
                    self.name, path, 0,
                    "bench.py no longer exports the multichip JSON "
                    "block (placements/s, bytes/flush, per-device "
                    "FLOPs vs device count on the node-axis mesh)",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "bench",
            old='"multichip"',
            new='"renamed_multichip"',
        )


@register
class BigworldExportRule(Rule):
    """Composed topology: bench.py exports the ``bigworld`` JSON block
    (placements/s, per-host bytes/flush, snapshot catch-up seconds for
    the million-node world driven by fan-out followers heading pod
    meshes) — the per-round proof that the composed follower × pod
    stack holds at world scale."""

    name = "bigworld-export"
    description = "bench.py exports the bigworld block"

    def check(self, ctx: Context) -> List[Finding]:
        path = ctx.path("bench")
        if '"bigworld"' not in ctx.source(path):
            return [
                Finding(
                    self.name, path, 0,
                    "bench.py no longer exports the bigworld JSON "
                    "block (placements/s, per-host bytes/flush, "
                    "snapshot catch-up on the fan-out × pod composed "
                    "topology)",
                )
            ]
        return []

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "bench",
            old='"bigworld"',
            new='"renamed_bigworld"',
        )


@register
class FederationMetricsRule(Rule):
    """Multi-region federation plane: every ``federation.*`` metric
    emitted by federation.py, cluster.py, server.py or api/http.py —
    literal first args of metric calls — is in the zero-registered
    ``FEDERATION_COUNTERS`` / ``FEDERATION_GAUGES`` registries
    (federation.py) and server.py preregisters both at construction:
    absence of a ``federation.wan_reads`` or
    ``federation.forwarded`` series must mean "single region,
    nothing ever crossed the WAN", never "not exported"."""

    name = "federation-metrics"
    description = "federation.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        federation_path = ctx.path("federation")
        registry = astutil.assigned_strings(
            ctx.tree(federation_path), "FEDERATION_COUNTERS"
        ) | astutil.assigned_strings(
            ctx.tree(federation_path), "FEDERATION_GAUGES"
        )
        if not registry:
            return [
                Finding(
                    self.name, federation_path, 0,
                    "could not find the FEDERATION_COUNTERS/"
                    "FEDERATION_GAUGES registries in federation.py",
                )
            ]
        problems: List[Finding] = []
        for key in ("federation", "cluster", "server", "api_http"):
            path = ctx.path(key)
            tree = ctx.tree(path)
            emitted: Set[str] = set()
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                if (
                    node.func.attr in astutil.METRIC_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("federation.")
                ):
                    emitted.add(node.args[0].value)
            unregistered = emitted - registry
            if unregistered:
                problems.append(
                    Finding(
                        self.name, path, 0,
                        "federation.* metrics emitted but not in "
                        "the FEDERATION_COUNTERS/FEDERATION_GAUGES "
                        "registries (they would be absent from "
                        "prometheus scrapes until the first WAN "
                        f"crossing): {sorted(unregistered)}",
                    )
                )
        server_src = ctx.source(ctx.path("server"))
        if "FEDERATION_COUNTERS" not in server_src:
            problems.append(
                Finding(
                    self.name, ctx.path("server"), 0,
                    "server.py no longer zero-registers the "
                    "federation.* family at construction "
                    "(FEDERATION_COUNTERS preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "federation",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.incr("federation.bogus_metric")\n'
            ),
        )


MIGRATED_RULES = (
    "stage-observed",
    "stage-orphans",
    "bench-stage-export",
    "span-registry",
    "device-metrics",
    "debug-bundle-device",
    "placement-metrics",
    "reason-vocab",
    "debug-bundle-placements",
    "admission-metrics",
    "latency-sweep",
)
