"""jit purity: functions reachable from a ``jax.jit`` decoration must
stay pure, and jit wrappers must not be minted per call.

A jitted function's Python body runs ONCE per trace-cache entry, not
once per call: a ``time.time()``, ``os.environ`` read, RNG draw,
``TRACE``/``Metrics`` emission or module-global mutation inside it
executes at trace time, bakes its value into the compiled executable,
and then silently never runs again — correct-looking on the first
call, wrong forever after.  The CPU tier-1 suite can't catch the
steady-state behavior difference, so this is a static pass.

Sub-checks:

* **impure-call** — a call to ``time.*``, ``os.environ``/
  ``os.getenv``, ``random.*``/``np.random.*``, ``print``, ``TRACE.*``
  or a Metrics emitter (``.incr/.set_gauge/.add_sample/.measure``)
  inside a function reachable from a jit root.
* **global-mutation** — a ``global`` statement inside a jit-reachable
  function (trace-time writes to module state).
* **fresh-jit** — ``jax.jit(lambda ...)`` inside a function body: a
  fresh lambda per call gets a fresh jit wrapper, so every invocation
  re-traces, re-lowers and re-compiles.  (The cached-factory pattern
  — jit of a named module function memoized in a module global — is
  fine and not flagged.)

Reachability is module-local: jit roots are ``@jax.jit`` /
``@functools.partial(jax.jit, ...)`` decorated defs plus ``X =
jax.jit(f)`` assignments; from a root, any locally-defined function
whose name is referenced in a reachable body is reachable (this
catches helpers passed to ``lax.scan`` and friends).  Cross-module
helpers are covered when their own module declares jit roots — true
for ops/score.py, the one module the kernels import helpers from.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from ..astutil import dotted_name, functions_by_name
from ..core import Context, Finding, Rule, register

# dotted-call prefixes that are impure at trace time
IMPURE_PREFIXES = (
    "time.",
    "os.environ",
    "os.getenv",
    "random.",
    "np.random.",
    "numpy.random.",
)
IMPURE_NAMES = {"print", "input", "open"}
EMITTER_ATTRS = {"incr", "set_gauge", "add_sample", "measure"}


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``functools.partial(jax.jit, ...)``."""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(node.func)
    return False


def _jit_roots(
    tree: ast.AST, defs: Dict[str, ast.FunctionDef]
) -> List[ast.FunctionDef]:
    roots: List[ast.FunctionDef] = []
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and any(_is_jit_expr(d) for d in node.decorator_list):
            roots.append(node)
        # X = jax.jit(f, ...) — f (or f.__wrapped__) by name
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and dotted_name(node.value.func) in ("jax.jit", "jit")
            and node.value.args
        ):
            target = node.value.args[0]
            if (
                isinstance(target, ast.Attribute)
                and target.attr == "__wrapped__"
            ):
                target = target.value
            if (
                isinstance(target, ast.Name)
                and target.id in defs
            ):
                roots.append(defs[target.id])
    return roots


def _reachable(
    roots: List[ast.FunctionDef],
    defs: Dict[str, ast.FunctionDef],
) -> List[ast.FunctionDef]:
    seen: Set[int] = set()
    out: List[ast.FunctionDef] = []
    stack = list(roots)
    while stack:
        fn = stack.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        out.append(fn)
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in defs
            ):
                stack.append(defs[node.id])
    return out


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    description = (
        "jit-reachable code is pure; no per-call jit of lambdas"
    )

    def check(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        for path in ctx.scan_files():
            tree = ctx.tree(path)
            defs = functions_by_name(tree)
            roots = _jit_roots(tree, defs)
            if roots:
                out.extend(
                    self._purity_findings(
                        path, _reachable(roots, defs)
                    )
                )
            out.extend(self._fresh_jit_findings(path, tree))
        return out

    @staticmethod
    def _own_body(fn: ast.FunctionDef):
        """Walk a function's body without descending into nested
        defs (those are separately reachable when referenced, so
        findings inside them attribute to the nested function)."""
        stack: List[ast.AST] = list(
            ast.iter_child_nodes(fn)
        )
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _purity_findings(
        self, path: str, fns: List[ast.FunctionDef]
    ) -> List[Finding]:
        out: List[Finding] = []
        for fn in fns:
            for node in self._own_body(fn):
                if isinstance(node, ast.Global):
                    out.append(
                        Finding(
                            self.name, path, node.lineno,
                            f"jit-reachable {fn.name}() declares "
                            "`global` — module state mutated at "
                            "trace time runs once per compile, "
                            "not once per call",
                        )
                    )
                if not isinstance(node, ast.Call):
                    continue
                reason = self._impure_call(node)
                if reason:
                    out.append(
                        Finding(
                            self.name, path, node.lineno,
                            f"jit-reachable {fn.name}() calls "
                            f"{reason} — executes at trace time "
                            "only, its value is baked into the "
                            "compiled executable",
                        )
                    )
        return out

    @staticmethod
    def _impure_call(node: ast.Call) -> Optional[str]:
        name = dotted_name(node.func)
        if name:
            if name in IMPURE_NAMES:
                return f"{name}()"
            for prefix in IMPURE_PREFIXES:
                if name == prefix.rstrip(".") or name.startswith(
                    prefix
                ):
                    return f"{name}()"
            if name.startswith("TRACE."):
                return f"{name}() (flight-recorder emission)"
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in EMITTER_ATTRS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return (
                f".{node.func.attr}(...) (metrics emission)"
            )
        return None

    def _fresh_jit_findings(
        self, path: str, tree: ast.AST
    ) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            for call in ast.walk(node):
                if (
                    isinstance(call, ast.Call)
                    and dotted_name(call.func)
                    in ("jax.jit", "jit")
                    and call.args
                    and isinstance(call.args[0], ast.Lambda)
                ):
                    out.append(
                        Finding(
                            self.name, path, call.lineno,
                            "jax.jit(lambda ...) inside "
                            f"{node.name}() builds a fresh jit "
                            "wrapper per call — every invocation "
                            "re-traces and re-compiles; hoist the "
                            "jitted kernel or cache the wrapper",
                        )
                    )
        return out

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "jit_purity",
        )
        return ctx.with_overrides(
            scan_files=[os.path.join(fixtures, "bad.py")]
        )

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "jit_purity",
        )
        return ctx.with_overrides(
            scan_files=[os.path.join(fixtures, "clean.py")]
        )
