"""Rule modules self-register on import (core.all_rules imports this
package).  Order here is the order rules run and report."""
from . import stage_accounting  # noqa: F401
from . import donation  # noqa: F401
from . import jit_purity  # noqa: F401
from . import locks  # noqa: F401
from . import config_drift  # noqa: F401
from . import concurrency  # noqa: F401
from . import kernel_contract  # noqa: F401
from . import concurrency_doc  # noqa: F401
from . import decision_ledger  # noqa: F401

MIGRATED_RULES = stage_accounting.MIGRATED_RULES
