"""Control-loop flight-data rules: the ``DECISION_SITES`` registry in
``nomad_tpu/decisions.py`` is the contract that every adaptive
decision site actually ledgers — both directions are checked
statically — and the ``slo.*`` / ``decision.*`` metric families are
zero-registered at Server construction like every other family."""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from .. import astutil
from ..core import Context, Finding, Rule, register
from .stage_accounting import DebugBundleDeviceRule


def decision_sites(tree: ast.AST) -> Dict[str, str]:
    """The literal ``DECISION_SITES`` dict (slug -> path key),
    annotated assignment or plain."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for target in targets:
            if (
                isinstance(target, ast.Name)
                and target.id == "DECISION_SITES"
                and isinstance(node.value, ast.Dict)
            ):
                return {
                    k.value: v.value
                    for k, v in zip(
                        node.value.keys, node.value.values
                    )
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                }
    return {}


def recorded_slugs(tree: ast.AST) -> Set[str]:
    """Site slugs a module ledgers: the literal first argument of
    ``DECISIONS.record("slug", ...)`` calls (any attribute path
    ending in ``.record`` on a ``DECISIONS``/``decisions`` object)
    and of ``self._record_decision("slug", ...)`` helper calls."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        dotted = astutil.dotted_name(node.func) or ""
        if dotted.endswith("._record_decision"):
            out.add(node.args[0].value)
        elif dotted.endswith(".record") and (
            "DECISIONS" in dotted or "decisions" in dotted
        ):
            out.add(node.args[0].value)
    return out


@register
class DecisionLedgerRule(Rule):
    """Check: every slug in the ``DECISION_SITES`` registry is
    ledgered by the module that owns it, every ``record("slug")``
    call site uses a registered slug, every slug has its
    ``decision.site.<slug>`` counter in ``DECISION_COUNTERS``, and
    server.py zero-registers the family at construction."""

    name = "decision-ledger"
    description = (
        "DECISION_SITES registry matches the record() call sites"
    )

    def check(self, ctx: Context) -> List[Finding]:
        dec_path = ctx.path("decisions")
        tree = ctx.tree(dec_path)
        sites = decision_sites(tree)
        problems: List[Finding] = []
        if not sites:
            return [
                Finding(
                    self.name, dec_path, 0,
                    "could not find the literal DECISION_SITES "
                    "registry in decisions.py",
                )
            ]
        counters = astutil.assigned_strings(
            tree, "DECISION_COUNTERS"
        )
        missing_counters = {
            slug
            for slug in sites
            if f"decision.site.{slug}" not in counters
        }
        if missing_counters:
            problems.append(
                Finding(
                    self.name, dec_path, 0,
                    "registered decision sites without a "
                    "decision.site.<slug> counter in "
                    "DECISION_COUNTERS (their firing would be "
                    "invisible on /v1/metrics): "
                    f"{sorted(missing_counters)}",
                )
            )
        # group the registry by owning module, then check both
        # directions per module: a registered slug must be recorded
        # there, and a recorded slug must be registered (anywhere —
        # helper modules may ledger a site its owner declares)
        by_module: Dict[str, Set[str]] = {}
        for slug, key in sites.items():
            by_module.setdefault(key, set()).add(slug)
        for key, slugs in sorted(by_module.items()):
            try:
                mod_path = ctx.path(key)
                mod_tree = ctx.tree(mod_path)
            except (KeyError, OSError):
                problems.append(
                    Finding(
                        self.name, dec_path, 0,
                        f"DECISION_SITES maps to unknown module "
                        f"key {key!r}",
                    )
                )
                continue
            recorded = recorded_slugs(mod_tree)
            silent = slugs - recorded
            if silent:
                problems.append(
                    Finding(
                        self.name, mod_path, 0,
                        "registered decision sites that never "
                        "record a DecisionRecord here (the ledger "
                        "would silently miss this control loop): "
                        f"{sorted(silent)}",
                    )
                )
            unregistered = recorded - set(sites)
            if unregistered:
                problems.append(
                    Finding(
                        self.name, mod_path, 0,
                        "record() call sites using slugs missing "
                        "from the DECISION_SITES registry: "
                        f"{sorted(unregistered)}",
                    )
                )
        server_path = ctx.path("server")
        server_src = ctx.source(server_path)
        if "DECISION_COUNTERS" not in server_src:
            problems.append(
                Finding(
                    self.name, server_path, 0,
                    "server.py no longer zero-registers the "
                    "decision.* family at construction "
                    "(DECISION_COUNTERS preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "batch_worker",
            append=(
                "def _nomadlint_bad_fixture():\n"
                '    DECISIONS.record("bogus_site", "x")\n'
            ),
        )


@register
class SLOMetricsRule(Rule):
    """Check: every ``slo.*`` / ``decision.*`` metric emitted by the
    engine and ledger is in the zero-registered ``SLO_*`` /
    ``DECISION_*`` registries, and server.py registers both at
    construction (absence-of-series must mean "never evaluated" /
    "site never fired", not "not exported")."""

    name = "slo-metrics"
    description = "slo.*/decision.* emissions are zero-registered"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        slo_path = ctx.path("slo")
        slo_tree = ctx.tree(slo_path)
        slo_registry = astutil.assigned_strings(
            slo_tree, "SLO_COUNTERS"
        ) | astutil.assigned_strings(slo_tree, "SLO_GAUGES")
        emitted = astutil.metric_names_emitted(slo_tree, "slo.")
        unregistered = emitted - slo_registry
        if not slo_registry:
            problems.append(
                Finding(
                    self.name, slo_path, 0,
                    "could not find the SLO_COUNTERS/SLO_GAUGES "
                    "registries in slo.py",
                )
            )
        elif unregistered:
            problems.append(
                Finding(
                    self.name, slo_path, 0,
                    "slo.* metrics emitted but not in the SLO_* "
                    "registries: " f"{sorted(unregistered)}",
                )
            )
        dec_path = ctx.path("decisions")
        dec_tree = ctx.tree(dec_path)
        dec_registry = astutil.assigned_strings(
            dec_tree, "DECISION_COUNTERS"
        ) | astutil.assigned_strings(dec_tree, "DECISION_GAUGES")
        dec_emitted = {
            name
            for name in astutil.metric_names_emitted(
                dec_tree, "decision."
            )
            # per-site counters are registered via the literal
            # decision.site.<slug> rows (decision-ledger rule);
            # dynamic f-string emissions don't surface here anyway
        }
        dec_unregistered = dec_emitted - dec_registry
        if dec_unregistered:
            problems.append(
                Finding(
                    self.name, dec_path, 0,
                    "decision.* metrics emitted but not in the "
                    "DECISION_* registries: "
                    f"{sorted(dec_unregistered)}",
                )
            )
        server_path = ctx.path("server")
        if "SLO_COUNTERS" not in ctx.source(server_path):
            problems.append(
                Finding(
                    self.name, server_path, 0,
                    "server.py no longer zero-registers the slo.* "
                    "family at construction (SLO_COUNTERS "
                    "preregister)",
                )
            )
        return problems

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._mutated(
            ctx, tmpdir, "slo",
            append=(
                "def _nomadlint_bad_fixture(metrics):\n"
                '    metrics.incr("slo.bogus_metric")\n'
            ),
        )


@register
class DebugBundleSLORule(DebugBundleDeviceRule):
    """Check: the operator debug bundle captures ``/v1/slo`` so a
    bundle from a misbehaving server says which objective was
    burning when the capture ran."""

    name = "debug-bundle-slo"
    description = "operator debug bundle captures /v1/slo"

    # quoted form: the cluster variant ("/v1/cluster/slo") must not
    # satisfy the local-status capture check
    NEEDLE = '"/v1/slo"'
    ENDPOINT = "/v1/slo"


@register
class DebugBundleDecisionsRule(DebugBundleDeviceRule):
    """Check: the operator debug bundle captures ``/v1/decisions``
    so the adaptive-decision flight data travels with the traces it
    cross-references."""

    name = "debug-bundle-decisions"
    description = "operator debug bundle captures /v1/decisions"

    NEEDLE = "/v1/decisions"
    ENDPOINT = "/v1/decisions"
