"""kernel-contract: the declared shape ladders of the production
kernels (chunk / storm / mesh) hold under ``jax.eval_shape``.

Recompile drift is invisible to the CPU tier-1 suite — a collapsed
pow2 bucket or a weak-type promotion only shows up as a multi-second
XLA compile in the accelerator hot path (a p99 cliff).  This rule
runs ``nomad_tpu/ops/contracts.py`` at lint time: every declared
ladder rung must be a distinct compiled signature, ``eval_shape``
must succeed on each, and output dtypes must stay inside the
declared closed set with no weak types.  It also AST-cross-checks
the contract's chunk ladder against ``batch_worker.CHUNK_BUCKETS``
so the contract cannot drift from the worker's live bucket policy.
"""
from __future__ import annotations

import ast
import importlib.util
import os
from typing import List, Optional, Tuple

from ..core import Context, Finding, Rule, register


def _chunk_buckets_literal(tree: ast.AST) -> Optional[Tuple[int, ...]]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Name)
                and target.id == "CHUNK_BUCKETS"
            ):
                vals = [
                    n.value
                    for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, int)
                ]
                return tuple(vals)
    return None


def _load_fixture_contracts(path: str):
    spec = importlib.util.spec_from_file_location(
        f"_kc_fixture_{abs(hash(path))}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.iter_contracts()


@register
class KernelContractRule(Rule):
    name = "kernel-contract"
    description = (
        "compiled-signature count == declared shape ladder; "
        "output dtype closure (no weak types)"
    )
    cross_file = True

    def check(self, ctx: Context) -> List[Finding]:
        from nomad_tpu.ops import contracts as live

        contracts_path = ctx.path("ops_contracts")
        findings: List[Finding] = []
        override = ctx.overrides.get("ops_contracts")
        if override is not None:
            try:
                contract_list = _load_fixture_contracts(override)
            except Exception as exc:  # noqa: BLE001
                return [
                    Finding(
                        self.name, override, 0,
                        f"contract module failed to load: {exc}",
                    )
                ]
            violations = live.check_contracts(contract_list)
            return [
                Finding(self.name, override, 0, v)
                for v in violations
            ]
        for v in live.check_contracts():
            findings.append(
                Finding(self.name, contracts_path, 0, v)
            )
        # ladder drift: the contract's chunk ladder must equal the
        # worker's live CHUNK_BUCKETS literal
        declared = _chunk_buckets_literal(
            ctx.tree(ctx.path("batch_worker"))
        )
        if declared is None:
            findings.append(
                Finding(
                    self.name, ctx.path("batch_worker"), 0,
                    "could not find the CHUNK_BUCKETS literal — "
                    "the kernel contract cross-check needs it",
                )
            )
        elif tuple(live.CHUNK_LADDER) != declared:
            findings.append(
                Finding(
                    self.name, contracts_path, 0,
                    f"contracts.CHUNK_LADDER {live.CHUNK_LADDER} "
                    "!= batch_worker.CHUNK_BUCKETS "
                    f"{declared} — the declared kernel ladder "
                    "drifted from the live chunk-width policy",
                )
            )
        return findings

    @classmethod
    def _fixture(cls, ctx: Context, which: str) -> Context:
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "kernel_contract",
        )
        return ctx.with_overrides(
            ops_contracts=os.path.join(fixtures, f"{which}.py")
        )

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._fixture(ctx, "bad")

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        return cls._fixture(ctx, "clean")
