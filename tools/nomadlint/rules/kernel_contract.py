"""kernel-contract: the declared shape ladders of the production
kernels (chunk / storm / mesh) hold under ``jax.eval_shape``.

Recompile drift is invisible to the CPU tier-1 suite — a collapsed
pow2 bucket or a weak-type promotion only shows up as a multi-second
XLA compile in the accelerator hot path (a p99 cliff).  This rule
runs ``nomad_tpu/ops/contracts.py`` at lint time: every declared
ladder rung must be a distinct compiled signature, ``eval_shape``
must succeed on each, and output dtypes must stay inside the
declared closed set with no weak types.  It also AST-cross-checks
the contract's chunk ladder against ``batch_worker.CHUNK_BUCKETS``
so the contract cannot drift from the worker's live bucket policy,
and requires the MULTI-host pod ladder (``MESH_HOST_WIDTHS`` plus
the ``mesh_host``/``storm_mesh`` contracts) — a pod resize walking
an undeclared width would recompile every process's kernels at once.
"""
from __future__ import annotations

import ast
import importlib.util
import os
from typing import List, Optional, Tuple

from ..core import Context, Finding, Rule, register


def _int_tuple_literal(
    tree: ast.AST, name: str
) -> Optional[Tuple[int, ...]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign):  # NAME: Tuple[...] = (...)
            targets = [node.target]
            value = node.value
        else:
            continue
        if value is None:
            continue
        for target in targets:
            if (
                isinstance(target, ast.Name)
                and target.id == name
            ):
                vals = [
                    n.value
                    for n in ast.walk(value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, int)
                ]
                return tuple(vals)
    return None


def _chunk_buckets_literal(tree: ast.AST) -> Optional[Tuple[int, ...]]:
    return _int_tuple_literal(tree, "CHUNK_BUCKETS")


def _load_fixture_contracts(path: str):
    spec = importlib.util.spec_from_file_location(
        f"_kc_fixture_{abs(hash(path))}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.iter_contracts()


@register
class KernelContractRule(Rule):
    name = "kernel-contract"
    description = (
        "compiled-signature count == declared shape ladder; "
        "output dtype closure (no weak types)"
    )
    cross_file = True

    def check(self, ctx: Context) -> List[Finding]:
        from nomad_tpu.ops import contracts as live

        contracts_path = ctx.path("ops_contracts")
        findings: List[Finding] = []
        override = ctx.overrides.get("ops_contracts")
        # multi-host ladder presence (override-aware): a contracts
        # module without a declared MESH_HOST_WIDTHS pod ladder lets
        # a pod resize recompile every process's kernels silently —
        # ROADMAP item 3 names this check explicitly
        ladder_path = override or contracts_path
        host_widths = _int_tuple_literal(
            ctx.tree(ladder_path), "MESH_HOST_WIDTHS"
        )
        if not host_widths:
            findings.append(
                Finding(
                    self.name, ladder_path, 0,
                    "no MESH_HOST_WIDTHS multi-host shape ladder "
                    "declared — pod recompiles can drift silently",
                )
            )
        # fan-out pod ladder (follower-headed meshes): same silent-
        # recompile exposure, multiplied by the follower count — and
        # _attach_pod's live width gate reads this literal, so its
        # absence would also disable the gate
        fanout_widths = _int_tuple_literal(
            ctx.tree(ladder_path), "MESH_FANOUT_WIDTHS"
        )
        if not fanout_widths:
            findings.append(
                Finding(
                    self.name, ladder_path, 0,
                    "no MESH_FANOUT_WIDTHS fan-out pod shape "
                    "ladder declared — follower-headed mesh "
                    "recompiles can drift silently",
                )
            )
        if override is not None:
            try:
                contract_list = _load_fixture_contracts(override)
            except Exception as exc:  # noqa: BLE001
                return findings + [
                    Finding(
                        self.name, override, 0,
                        f"contract module failed to load: {exc}",
                    )
                ]
            violations = live.check_contracts(contract_list)
            return findings + [
                Finding(self.name, override, 0, v)
                for v in violations
            ]
        # the live module's pod ladder must be wired into real
        # contracts, not just declared: one rung per width for both
        # the chained runner and the sharded storm solve
        names = {c.name for c in live.iter_contracts()}
        for required in (
            "mesh_host", "storm_mesh",
            "mesh_fanout", "storm_fanout",
        ):
            if required not in names:
                findings.append(
                    Finding(
                        self.name, contracts_path, 0,
                        f"no '{required}' contract in "
                        "iter_contracts() — the declared multi-host "
                        "ladder is not checked against any kernel",
                    )
                )
        for v in live.check_contracts():
            findings.append(
                Finding(self.name, contracts_path, 0, v)
            )
        # ladder drift: the contract's chunk ladder must equal the
        # worker's live CHUNK_BUCKETS literal
        declared = _chunk_buckets_literal(
            ctx.tree(ctx.path("batch_worker"))
        )
        if declared is None:
            findings.append(
                Finding(
                    self.name, ctx.path("batch_worker"), 0,
                    "could not find the CHUNK_BUCKETS literal — "
                    "the kernel contract cross-check needs it",
                )
            )
        elif tuple(live.CHUNK_LADDER) != declared:
            findings.append(
                Finding(
                    self.name, contracts_path, 0,
                    f"contracts.CHUNK_LADDER {live.CHUNK_LADDER} "
                    "!= batch_worker.CHUNK_BUCKETS "
                    f"{declared} — the declared kernel ladder "
                    "drifted from the live chunk-width policy",
                )
            )
        return findings

    @classmethod
    def _fixture(cls, ctx: Context, which: str) -> Context:
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "kernel_contract",
        )
        return ctx.with_overrides(
            ops_contracts=os.path.join(fixtures, f"{which}.py")
        )

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._fixture(ctx, "bad")

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        return cls._fixture(ctx, "clean")
