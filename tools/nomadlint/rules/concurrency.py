"""Whole-program concurrency rules over the flowgraph core.

* **shared-state-guard** — static race detector: any attribute of a
  shared singleton written from one thread entry and touched from
  another must share a lock across both sites, or carry a justified
  ``SHARED_STATE_ALLOWLIST`` entry.  Findings name both access sites
  and both thread entries.  The ``NOMAD_TPU_TSAN=1`` runtime
  sanitizer (nomad_tpu/tsan.py) checks the same allowlist from the
  other direction: every runtime-observed conflicting pair must be
  lock-ordered or allowlisted here, so the list can't grow stale
  entries in either direction.
* **blocking-while-locked** — no lock-holding call may transitively
  reach a blocking op (``block_until_ready``, ``device_put``/
  ``device_get``, sockets, ``time.sleep``, event waits): a wedged
  device call under a lock parks every thread that needs it — the
  wedge class that ate the r03–r05 bench rounds.  ``Condition.wait``
  under its own lock is exempt (it releases the lock).

Both rules read the cross-file flowgraph, so a ``--files``-narrowed
run computes it from the FULL module set (``cross_file = True``) —
a narrowed run can't false-pass by hiding one side of a race pair.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from ..core import Context, Finding, Rule, register
from ..flowgraph import (
    blocking_op,
    entries_conflict,
    flowgraph,
)

# (family, attr regex) -> one-line justification.  Every entry must
# match at least one live race pair on a full run — stale entries are
# themselves findings, so the allowlist can't rot.  The TSAN soak
# (tests/test_tsan.py) asserts runtime-observed conflicts stay inside
# this list.
SHARED_STATE_ALLOWLIST: Tuple[Tuple[str, str, str], ...] = (
    (
        "StateStore",
        r"jobs|evals|allocs|deployments|namespaces|job_versions"
        r"|scaling_events|scaling_policies|scheduler_config"
        r"|_scaling_by_target|_index|_table_index",
        "deliberately lock-free read side: CPython dict/int reads "
        "are GIL-atomic and every mutation runs under _lock; "
        "schedulers fence cross-table consistency via "
        "snapshot_min_index, so a racy read sees a complete older "
        "index, never a torn row",
    ),
    (
        "DeviceSupervisor",
        r"_device_ready",
        "monotonic bool latch (False->True once the device first "
        "answers); GIL-atomic store and both writers converge on "
        "True",
    ),
    (
        "DeviceSupervisor",
        r"_state|backend_epoch",
        "state/epoch reads outside the lock are advisory fast-path "
        "checks; every transition revalidates and writes under "
        "_lock, and consumers key caches by the epoch so a stale "
        "read costs one extra resync, never stale device buffers",
    ),
    (
        "DeviceSupervisor",
        r"last_error|last_incident|_incident|_recover_streak"
        r"|canary_ok|canary_fail|probe_timeouts|watchdog_trips",
        "incident/counter bookkeeping: single GIL-atomic scalar "
        "stores whose worst-case race is one miscounted or stale "
        "/v1/device status field, never scheduling state",
    ),
    (
        "Server",
        r"_running|_leader_established",
        "lifecycle latches: bool stores are GIL-atomic and every "
        "consumer loop (sweeper, HTTP heartbeat path) re-checks "
        "per tick, so a racing stop()/establish is observed one "
        "tick later — shutdown needs no lock ordering (the TSAN "
        "soak first caught this pair at runtime)",
    ),
    (
        "DeviceSupervisor",
        r"_warm_hooks",
        "warm-hook registration list: GIL-atomic append from "
        "leadership setup; the probe thread iterates the whole "
        "list per recovery pass, and a hook registered mid-pass "
        "is picked up on the next one",
    ),
    (
        "Worker",
        r"_replay_pool",
        "lazy pool singleton: one writer (the worker thread); "
        "stop() reads a complete-or-None reference (GIL-atomic "
        "object store) and shuts it down after joining the thread",
    ),
    (
        "Worker",
        r"_thread",
        "generation latch: start() rebinds the reference "
        "(GIL-atomic object store) and run() threads compare it "
        "against current_thread() per loop tick — a straggler from "
        "a previous leadership generation observes the new binding "
        "one tick later and exits; both orderings are safe",
    ),
    (
        "Server",
        r"_clients",
        "node->connection registry: dict get/set are GIL-atomic; a "
        "concurrent re-register keeps one of the two live "
        "connections and the client's next register heals it",
    ),
    (
        "Server",
        r"_heartbeat_deadlines|_down_wave",
        "per-node deadline map + the pending mass-death gather set: "
        "HTTP threads set/pop single keys, the sweeper iterates "
        "list() snapshots and pops expired ones; dict ops are "
        "GIL-atomic, a deadline racing its own expiry is re-armed "
        "by the node's next heartbeat, and the wave commit "
        "re-verifies each member against the live store (already-"
        "down and re-heartbeated nodes drop out)",
    ),
    (
        "Tracer",
        r"_by_id",
        "hot-path span append reads the ring dict lock-free (the "
        "O(1)-append/<50us contract); dict get is GIL-atomic and "
        "eviction under _lock swaps whole trace objects, so a "
        "racing lookup sees a complete (old) trace",
    ),
    (
        "Worker",
        r"_pod",
        "set-once pod-service latch: _attach_pod checks-then-binds "
        "a complete PodService (GIL-atomic object store) from the "
        "mesh bring-up path and is idempotent across leadership "
        "rebuilds; dispose() closes it only after stop() joined "
        "the worker thread, so no launch can race the teardown",
    ),
    (
        "Worker",
        r"_backend_epoch|_cand_cache|_mask_cache|_port_col_cache"
        r"|_dev_codes_cache|_dev_aff_cache|_donate_carries"
        r"|_launch_ewma|_launch_ewma_seed|_mesh_ewma_seed|_mesh"
        r"|_mesh_hosts"
        r"|_sharded_runners|_mirror_dirty|_mirror_dirty_sharded"
        r"|_usage_cache|_usage_cache_sharded",
        "the documented wedge-bypass epoch protocol: "
        "_on_device_transition must flush these WITHOUT locks (a "
        "wedged sacrificial thread may hold _usage_cache_lock "
        "forever), so it rebinds fresh objects — never mutates in "
        "place — and every consumer keys entries by _backend_epoch "
        "and discards stale publishes",
    ),
)


def _allowlisted(fam: str, attr: str) -> int:
    """Index of the matching allowlist entry, or -1."""
    for i, (afam, pattern, _why) in enumerate(
        SHARED_STATE_ALLOWLIST
    ):
        if afam == fam and re.fullmatch(pattern, attr):
            return i
    return -1


def _fixture_ctx(ctx: Context, sub: str, name: str) -> Context:
    fixtures = os.path.join(
        os.path.dirname(os.path.dirname(__file__)),
        "fixtures",
        sub,
    )
    return ctx.with_overrides(
        scan_files=[os.path.join(fixtures, name)]
    )


@register
class SharedStateGuardRule(Rule):
    name = "shared-state-guard"
    description = (
        "cross-thread shared attributes are consistently locked "
        "or allowlisted"
    )
    cross_file = True

    def check(self, ctx: Context) -> List[Finding]:
        g = flowgraph(ctx)
        findings: List[Finding] = []
        used: Set[int] = set()
        for (fam, attr), sites in sorted(g.shared_access.items()):
            pair = None
            for a in sites:
                if a.kind != "w":
                    continue
                for b in sites:
                    if not entries_conflict(a.entry, b.entry):
                        continue
                    if a.guards & b.guards:
                        continue
                    pair = (a, b)
                    break
                if pair:
                    break
            if pair is None:
                continue
            idx = _allowlisted(fam, attr)
            if idx >= 0:
                used.add(idx)
                continue
            a, b = pair
            kind_b = "written" if b.kind == "w" else "read"
            findings.append(
                Finding(
                    self.name,
                    a.path,
                    a.line,
                    f"{fam}.{attr} is written at "
                    f"{os.path.basename(a.path)}:{a.line} "
                    f"(thread entry {a.entry.render()}) and "
                    f"{kind_b} at "
                    f"{os.path.basename(b.path)}:{b.line} "
                    f"(thread entry {b.entry.render()}) with no "
                    "common lock "
                    f"(guards: {sorted(a.guards) or 'none'} vs "
                    f"{sorted(b.guards) or 'none'}) — guard both "
                    "sites with one lock or add a justified "
                    "SHARED_STATE_ALLOWLIST entry "
                    "(tools/nomadlint/rules/concurrency.py)",
                )
            )
        if "scan_files" not in ctx.overrides:
            for i, (fam, pattern, _why) in enumerate(
                SHARED_STATE_ALLOWLIST
            ):
                if i not in used:
                    findings.append(
                        Finding(
                            self.name,
                            os.path.abspath(__file__),
                            0,
                            "stale SHARED_STATE_ALLOWLIST entry "
                            f"({fam!r}, {pattern!r}): no live race "
                            "pair matches it — remove it so the "
                            "allowlist can't rot",
                        )
                    )
        return findings

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return _fixture_ctx(ctx, "shared_state", "bad.py")

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        return _fixture_ctx(ctx, "shared_state", "clean.py")


@register
class BlockingWhileLockedRule(Rule):
    name = "blocking-while-locked"
    description = (
        "no lock-holding call transitively reaches a blocking op"
    )
    cross_file = True

    def check(self, ctx: Context) -> List[Finding]:
        g = flowgraph(ctx)
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()
        for qual in sorted(g.methods):
            info = g.methods[qual]
            for call in info.calls:
                if not call.held:
                    continue
                locks = ", ".join(sorted(call.held))
                op = blocking_op(call, g.lock_attr_names)
                if op is not None:
                    key = (info.path, call.line, op)
                    if key not in seen:
                        seen.add(key)
                        findings.append(
                            Finding(
                                self.name,
                                info.path,
                                call.line,
                                f"{qual} calls blocking {op} "
                                f"while holding {locks} — a "
                                "wedged call parks every thread "
                                "queued on the lock (the r03–r05 "
                                "bench wedge class); move the "
                                "blocking op outside the critical "
                                "section",
                            )
                        )
                callee = g.resolve(info.cls, call, info)
                if callee is None:
                    continue
                for op, path in sorted(
                    g.blocking.get(callee.qualname, {}).items()
                ):
                    key = (info.path, call.line, op)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(
                        Finding(
                            self.name,
                            info.path,
                            call.line,
                            f"{qual} holds {locks} while calling "
                            f"{callee.qualname}, which reaches "
                            f"blocking {op} ({path}) — a wedged "
                            "call parks every thread queued on "
                            "the lock; move the blocking op "
                            "outside the critical section or "
                            "suppress with the documented wedge "
                            "recovery story",
                        )
                    )
        return findings

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return _fixture_ctx(ctx, "blocking", "bad.py")

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        return _fixture_ctx(ctx, "blocking", "clean.py")
