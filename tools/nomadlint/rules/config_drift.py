"""Config/registry drift: every ``NOMAD_TPU_*`` environment knob must
be registered in ``nomad_tpu/envknobs.py`` and documented in the
``docs/ARCHITECTURE.md`` knob table — in both directions, so a new
knob can't ship undocumented and a removed one can't haunt the docs.

This generalizes the metric/span registry checks (4–10 of the
stage-accounting family) to the configuration surface: the registry
is the single place an operator looks up a knob, and the lint is what
keeps it complete.  Usage is collected by AST scan for full-match
``NOMAD_TPU_[A-Z0-9_]+`` string constants (docstrings excluded) over
``nomad_tpu/``, ``bench.py`` and ``tests/`` — reads through
``os.environ``/``os.getenv``, constants like ``FAULT_ENV``, and env
dicts handed to subprocesses all surface the name as exactly such a
literal.

Four directions checked:

1. every knob used in code is registered in ``ENV_KNOBS``;
2. every registered knob appears in the docs table;
3. every ``NOMAD_TPU_*`` name in the docs table is registered
   (no stale doc rows);
4. every registered knob is actually read somewhere (no dead
   registry rows).
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Set

from ..astutil import dict_key_strings, string_constants
from ..core import Context, Finding, Rule, register

ENV_RE = re.compile(r"^NOMAD_TPU_[A-Z0-9_]+$")
DOC_ENV_RE = re.compile(r"NOMAD_TPU_[A-Z0-9_]+")


@register
class ConfigDriftRule(Rule):
    name = "config-drift"
    description = (
        "NOMAD_TPU_* knobs registered in envknobs.py + documented"
    )
    # needs BOTH sides of every pair (usage scan + registry + docs
    # table): a --files-narrowed run sees only a slice of the reads,
    # so direction 4 (dead registry rows) would false-fire and
    # direction 1 would false-pass — the runner always hands this
    # rule the full file set
    cross_file = True

    def _usage(self, ctx: Context) -> Dict[str, List]:
        """knob -> [(path, line), ...] across the scan scope."""
        override = ctx.overrides.get("scan_files")
        if override is not None:
            files = list(override)
        else:
            files = ctx.scan_files()
            files.append(ctx.path("bench"))
            tests_dir = os.path.join(ctx.repo, "tests")
            if os.path.isdir(tests_dir):
                files.extend(
                    os.path.join(tests_dir, fn)
                    for fn in sorted(os.listdir(tests_dir))
                    if fn.endswith(".py")
                )
        envknobs = ctx.path("envknobs")
        out: Dict[str, List] = {}
        for path in files:
            if path == envknobs or path == ctx.default_path(
                "envknobs"
            ):
                continue  # the registry itself
            for value, line in string_constants(ctx.tree(path)):
                if ENV_RE.match(value):
                    out.setdefault(value, []).append(
                        (path, line)
                    )
        return out

    def check(self, ctx: Context) -> List[Finding]:
        envknobs_path = ctx.path("envknobs")
        doc_path = ctx.path("arch_doc")
        findings: List[Finding] = []
        try:
            registry = dict_key_strings(
                ctx.tree(envknobs_path), "ENV_KNOBS"
            )
        except OSError:
            return [
                Finding(
                    self.name, envknobs_path, 0,
                    "central env-knob registry "
                    "nomad_tpu/envknobs.py is missing",
                )
            ]
        registered = {n for n in registry if ENV_RE.match(n)}
        if not registered:
            return [
                Finding(
                    self.name, envknobs_path, 0,
                    "could not find the ENV_KNOBS registry "
                    "literal in nomad_tpu/envknobs.py",
                )
            ]
        documented: Set[str] = set()
        try:
            doc_src = ctx.source(doc_path)
        except OSError:
            doc_src = ""
            findings.append(
                Finding(
                    self.name, doc_path, 0,
                    "docs knob table missing (docs/ARCHITECTURE.md"
                    " not found)",
                )
            )
        for line in doc_src.splitlines():
            if line.lstrip().startswith("|"):
                documented |= set(DOC_ENV_RE.findall(line))

        usage = self._usage(ctx)
        for knob in sorted(set(usage) - registered):
            path, line = usage[knob][0]
            findings.append(
                Finding(
                    self.name, path, line,
                    f"env knob {knob} is read here but missing "
                    "from the ENV_KNOBS registry "
                    "(nomad_tpu/envknobs.py) — new knobs can't "
                    "ship unregistered",
                )
            )
        for knob in sorted(registered - documented):
            findings.append(
                Finding(
                    self.name, envknobs_path, 0,
                    f"env knob {knob} is registered but missing "
                    "from the docs/ARCHITECTURE.md knob table",
                )
            )
        for knob in sorted(documented - registered):
            findings.append(
                Finding(
                    self.name, doc_path, 0,
                    f"docs table documents {knob} but it is not "
                    "in the ENV_KNOBS registry — stale doc row "
                    "or missing registration",
                )
            )
        # direction 4 needs the FULL usage scan to be meaningful: a
        # --files/fixture narrowing sees only a slice of the reads,
        # so every other registered knob would read as dead
        if "scan_files" not in ctx.overrides:
            for knob in sorted(registered - set(usage)):
                findings.append(
                    Finding(
                        self.name, envknobs_path, 0,
                        f"env knob {knob} is registered but never "
                        "read anywhere — dead registry row",
                    )
                )
        return findings

    @classmethod
    def _fixture_ctx(cls, ctx: Context, which: str) -> Context:
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "config_drift",
        )
        return ctx.with_overrides(
            scan_files=[os.path.join(fixtures, f"{which}.py")],
            envknobs=os.path.join(fixtures, "envknobs.py"),
            arch_doc=os.path.join(fixtures, "docs.md"),
        )

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        return cls._fixture_ctx(ctx, "bad")

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        return cls._fixture_ctx(ctx, "clean")
