"""Donation safety: arguments passed to a ``donate_argnums``/
``donate_argnames``-jitted callable must not be read again in the
enclosing scope after the call.

Buffer donation is a no-op on the CPU backend, so a use-after-donate
slips through every tier-1 test and only corrupts on real
accelerators — exactly the bug class static analysis has to own
(ROADMAP item 1's accelerator capture is the first time these paths
run for real).

Two sub-checks:

* **read-after-donate** — a donated local name (or the base name of a
  donated ``x[i]``/``x.attr`` expression, and the ``*args``/
  ``**kwargs`` names of a starred donating call) is read again after
  the call, before any rebinding.  Calls inside loops also treat
  reads earlier in the loop body as "after" (the next iteration
  re-executes them) unless the loop rebinds the name first — the
  ``for col in ...`` iteration target is rebound at the loop header,
  so patterns like the mirror-sync loop stay clean.
* **persistent-donation** — the donated expression is rooted in
  ``self.<attr>`` state (directly or through local aliases).
  Donating a buffer a cache still references is a use-after-donate
  on the *next* call unless the cache slot is overwritten before any
  later read; such sites must be individually verified and carry a
  justified suppression.

Donating callables are discovered, not hardcoded: any ``jax.jit(...)``
call carrying ``donate_argnums``/``donate_argnames`` marks its
assignment target (and any function that returns it — the lazy
factory pattern ``ops/batch.py`` uses) as donating, across every
scanned module by symbol name.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, Rule, register


@dataclass
class _DonationSpec:
    positions: Set[int] = field(default_factory=set)
    keywords: Set[str] = field(default_factory=set)

    def merge(self, other: "_DonationSpec") -> None:
        self.positions |= other.positions
        self.keywords |= other.keywords


def _jit_donation_spec(
    call: ast.Call, local_defs: Dict[str, ast.FunctionDef]
) -> Optional[_DonationSpec]:
    """The donation spec of a ``jax.jit(...)`` call, or None when it
    donates nothing.  ``donate_argnames`` are mapped to positional
    indices when the wrapped function's def is resolvable in the
    same module (callers pass those args positionally too)."""
    from ..astutil import dotted_name

    if dotted_name(call.func) not in ("jax.jit", "jit"):
        return None
    spec = _DonationSpec()
    argnames: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(
                    n.value, int
                ):
                    spec.positions.add(n.value)
        elif kw.arg == "donate_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(
                    n.value, str
                ):
                    argnames.add(n.value)
    if not spec.positions and not argnames:
        return None
    spec.keywords |= argnames
    if argnames and call.args:
        # resolve the wrapped callable (possibly `f.__wrapped__`)
        target = call.args[0]
        if (
            isinstance(target, ast.Attribute)
            and target.attr == "__wrapped__"
        ):
            target = target.value
        if isinstance(target, ast.Name):
            fn = local_defs.get(target.id)
            if fn is not None:
                params = [a.arg for a in fn.args.args]
                for name in argnames:
                    if name in params:
                        spec.positions.add(params.index(name))
    return spec


def _scope_nodes(scope: ast.AST):
    """Walk a scope's own statements without descending into nested
    function/class bodies (those are their own scopes)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node,
            (
                ast.FunctionDef,
                ast.AsyncFunctionDef,
                ast.Lambda,
                ast.ClassDef,
            ),
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _collect_donating_symbols(
    trees: Dict[str, ast.AST]
) -> Dict[str, _DonationSpec]:
    """Module-level donating symbols across all scanned files, by
    bare name: variables assigned a donating jit, and functions that
    return one (factories)."""
    from ..astutil import functions_by_name

    symbols: Dict[str, _DonationSpec] = {}
    for tree in trees.values():
        local_defs = functions_by_name(tree)
        donating_locals: Dict[Tuple[int, str], _DonationSpec] = {}

        def note(scope_id: int, name: str, spec: _DonationSpec):
            key = (scope_id, name)
            if key in donating_locals:
                donating_locals[key].merge(spec)
            else:
                donating_locals[key] = _DonationSpec(
                    set(spec.positions), set(spec.keywords)
                )

        # pass 1: direct assignments/returns of donating jits
        scopes = [(0, tree)] + [
            (id(fn), fn) for fn in local_defs.values()
        ]
        for scope_id, scope in scopes:
            for node in _scope_nodes(scope):
                if (
                    scope_id != 0
                    and isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Call)
                ):
                    # `return jax.jit(..., donate_*=...)` makes the
                    # enclosing function a donating factory
                    spec = _jit_donation_spec(
                        node.value, local_defs
                    )
                    if spec is not None:
                        symbols.setdefault(
                            scope.name, _DonationSpec()
                        ).merge(spec)
                    continue
                if not isinstance(node, ast.Assign):
                    continue
                spec = (
                    _jit_donation_spec(node.value, local_defs)
                    if isinstance(node.value, ast.Call)
                    else None
                )
                if spec is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        note(scope_id, target.id, spec)
                        if scope_id == 0 or any(
                            isinstance(g, ast.Global)
                            and target.id in g.names
                            for g in ast.walk(scope)
                        ):
                            note(0, target.id, spec)
        # pass 2 (fixpoint): aliases and factory returns
        changed = True
        while changed:
            changed = False
            for scope_id, scope in scopes:
                for node in _scope_nodes(scope):
                    if (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Name)
                    ):
                        src = donating_locals.get(
                            (scope_id, node.value.id)
                        ) or donating_locals.get(
                            (0, node.value.id)
                        )
                        if src is None:
                            continue
                        for target in node.targets:
                            if (
                                isinstance(target, ast.Name)
                                and (scope_id, target.id)
                                not in donating_locals
                            ):
                                note(scope_id, target.id, src)
                                changed = True
                if scope_id == 0:
                    continue
                # a function returning a donating name is a factory
                fn = scope
                for node in _scope_nodes(fn):
                    if (
                        isinstance(node, ast.Return)
                        and isinstance(node.value, ast.Name)
                    ):
                        src = donating_locals.get(
                            (scope_id, node.value.id)
                        ) or donating_locals.get(
                            (0, node.value.id)
                        )
                        if src is not None and (
                            fn.name not in symbols
                            or symbols[fn.name].positions
                            != src.positions
                        ):
                            symbols.setdefault(
                                fn.name, _DonationSpec()
                            ).merge(src)
        for (scope_id, name), spec in donating_locals.items():
            if scope_id == 0:
                symbols.setdefault(name, _DonationSpec()).merge(
                    spec
                )
    return symbols


def _root_name(node: ast.AST) -> Optional[str]:
    """Base Name of an expression like ``x``, ``x[i]``, ``x.a[j]``;
    None for anything not rooted at a local name."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _data_nodes(expr: ast.AST):
    """Walk an expression yielding data-position nodes only: the
    callee of a Call is skipped (a bound method reference like
    ``self._chunk_slice`` is not buffer state)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        yield node
        for fieldname, value in ast.iter_fields(node):
            if isinstance(node, ast.Call) and fieldname == "func":
                continue
            if isinstance(value, ast.AST):
                stack.append(value)
            elif isinstance(value, list):
                stack.extend(
                    v for v in value if isinstance(v, ast.AST)
                )


def _is_self_rooted(node: ast.AST) -> bool:
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
        if (
            isinstance(node, ast.Name)
            and node.id == "self"
        ):
            return True
    return isinstance(node, ast.Name) and node.id == "self"


class _FnIndex:
    """Per-function name-binding/read index for the dataflow scan."""

    def __init__(self, fn: ast.FunctionDef) -> None:
        self.fn = fn
        self.reads: Dict[str, List[int]] = {}
        self.binds: Dict[str, List[int]] = {}
        # name -> RHS of its simple assignments (alias tracking)
        self.sources: Dict[str, List[ast.AST]] = {}
        self.loops: List[ast.AST] = []
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.While)):
                self.loops.append(node)
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    self.reads.setdefault(node.id, []).append(
                        node.lineno
                    )
                else:
                    self.binds.setdefault(node.id, []).append(
                        node.lineno
                    )
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.sources.setdefault(
                                n.id, []
                            ).append(node.value)
            if isinstance(node, ast.For):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        self.sources.setdefault(
                            n.id, []
                        ).append(node.iter)

    def persistent(self, name: str, seen: Set[str] = None) -> bool:
        """Whether ``name`` may alias state reachable from self.*
        (through any of its assignment sources, transitively).
        Callee positions are skipped: ``self.helper(x)`` flows data
        through ``x``, not through the bound method."""
        if seen is None:
            seen = set()
        if name in seen:
            return False
        seen.add(name)
        for src in self.sources.get(name, []):
            for node in _data_nodes(src):
                if _is_self_rooted(node) and isinstance(
                    node, (ast.Attribute, ast.Subscript)
                ):
                    return True
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ):
                    if node.id != name and self.persistent(
                        node.id, seen
                    ):
                        return True
        return False

    def read_after(
        self, name: str, call: ast.Call
    ) -> Optional[int]:
        """Line of a read of ``name`` after ``call`` (before any
        rebinding), or a next-iteration read when the call sits in a
        loop; None when no hazardous read exists."""
        end = getattr(call, "end_lineno", call.lineno)
        reads = sorted(self.reads.get(name, []))
        binds = sorted(self.binds.get(name, []))
        next_bind = next((b for b in binds if b > end), None)
        for r in reads:
            # a read on the rebinding line itself still evaluates
            # before the new binding takes effect (x = x + 1)
            if r > end and (next_bind is None or r <= next_bind):
                return r
        # loop wrap-around: the call's innermost enclosing loop
        loop = None
        for cand in self.loops:
            if (
                cand.lineno <= call.lineno
                and getattr(cand, "end_lineno", cand.lineno)
                >= end
            ):
                if loop is None or cand.lineno > loop.lineno:
                    loop = cand
        if loop is None:
            return None
        loop_end = getattr(loop, "end_lineno", loop.lineno)
        in_loop_reads = [
            r
            for r in reads
            if loop.lineno <= r <= loop_end and r <= end
        ]
        if not in_loop_reads:
            return None
        # safe when the loop rebinds the name before its first read
        # in iteration order (the for-target binds at the header)
        loop_binds = [
            b
            for b in binds
            if loop.lineno <= b <= loop_end
        ]
        if isinstance(loop, ast.For):
            for n in ast.walk(loop.target):
                if (
                    isinstance(n, ast.Name)
                    and n.id == name
                ):
                    loop_binds.append(loop.lineno)
        first_read = min(in_loop_reads)
        if loop_binds and min(loop_binds) <= first_read:
            return None
        return first_read


@register
class DonationSafetyRule(Rule):
    name = "donation-safety"
    description = (
        "no argument of a donating jit call is read after the call"
    )

    def check(self, ctx: Context) -> List[Finding]:
        trees = {
            path: ctx.tree(path) for path in ctx.scan_files()
        }
        symbols = _collect_donating_symbols(trees)
        if not symbols:
            return []
        out: List[Finding] = []
        seen = set()
        for path, tree in trees.items():
            for fn in [
                n
                for n in ast.walk(tree)
                if isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)
                )
            ]:
                # nested defs are analyzed both inside their parent
                # (closure reads count) and standalone — dedupe
                for f in self._check_function(path, fn, symbols):
                    key = (f.path, f.line, f.message)
                    if key not in seen:
                        seen.add(key)
                        out.append(f)
        return out

    def _check_function(
        self,
        path: str,
        fn: ast.FunctionDef,
        symbols: Dict[str, _DonationSpec],
    ) -> List[Finding]:
        index = _FnIndex(fn)
        # names rebound by the assignment consuming a call's value
        # (``buf = patch(buf, ...)``): the donated input is replaced
        # by the call's output before any later read can happen, so
        # reads after the call see the NEW binding — the idiomatic
        # safe donation pattern, not a use-after-donate
        rebound_at_call: Dict[int, Set[str]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets = [node.target]
            else:
                continue
            names = {
                n.id
                for t in targets
                for n in ast.walk(t)
                if isinstance(n, ast.Name)
            }
            if not names:
                continue
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call):
                    rebound_at_call.setdefault(
                        id(sub), set()
                    ).update(names)
        # local aliases of donating callables: x = factory();
        # y = x; fn = y  (conditional branches make a name only
        # *potentially* donating — still analyzed)
        donating: Dict[str, _DonationSpec] = {}
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                spec: Optional[_DonationSpec] = None
                v = node.value
                if (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in symbols
                ):
                    spec = symbols[v.func.id]
                elif (
                    isinstance(v, ast.Name)
                    and v.id in donating
                ):
                    spec = donating[v.id]
                if spec is None:
                    continue
                for t in node.targets:
                    if (
                        isinstance(t, ast.Name)
                        and t.id not in donating
                    ):
                        donating[t.id] = spec
                        changed = True

        out: List[Finding] = []
        for call in [
            n for n in ast.walk(fn) if isinstance(n, ast.Call)
        ]:
            spec: Optional[_DonationSpec] = None
            callee = "?"
            if isinstance(call.func, ast.Name):
                if call.func.id in donating:
                    spec = donating[call.func.id]
                    callee = call.func.id
            if spec is None and (
                isinstance(call.func, ast.Call)
                and isinstance(call.func.func, ast.Name)
                and call.func.func.id in symbols
            ):
                # direct factory()(args...) invocation
                spec = symbols[call.func.func.id]
                callee = call.func.func.id
            if spec is None:
                continue
            donated_exprs: List[ast.AST] = []
            pos = 0
            for arg in call.args:
                if isinstance(arg, ast.Starred):
                    # positions beyond this are unknowable: the
                    # whole starred tuple is treated as donated
                    donated_exprs.append(arg.value)
                    pos = 10**6
                    continue
                if pos in spec.positions:
                    donated_exprs.append(arg)
                pos += 1
            for kw in call.keywords:
                if kw.arg is None:
                    # **kwargs: the dict may carry donated keywords
                    if spec.keywords:
                        donated_exprs.append(kw.value)
                elif kw.arg in spec.keywords:
                    donated_exprs.append(kw.value)
            for expr in donated_exprs:
                name = _root_name(expr)
                if name is None:
                    if _is_self_rooted(expr):
                        out.append(
                            Finding(
                                self.name, path, call.lineno,
                                f"{callee}() donates an argument "
                                "rooted in self.* state — a "
                                "donated cache buffer is a "
                                "use-after-donate on the next "
                                "access unless the slot is "
                                "overwritten first",
                            )
                        )
                    continue
                # the call's own assignment rebinding the donated
                # name to its output makes later reads (including
                # next loop iterations) see the fresh buffer — but
                # a persistent self.* alias still holds the donated
                # one, so that check below still applies
                rebound = name in rebound_at_call.get(
                    id(call), ()
                )
                read_line = (
                    None
                    if rebound
                    else index.read_after(name, call)
                )
                if read_line is not None:
                    out.append(
                        Finding(
                            self.name, path, call.lineno,
                            f"argument {name!r} donated to "
                            f"{callee}() is read again at line "
                            f"{read_line} — use-after-donate "
                            "only corrupts on real accelerators "
                            "(CPU ignores donation)",
                        )
                    )
                elif index.persistent(name):
                    out.append(
                        Finding(
                            self.name, path, call.lineno,
                            f"argument {name!r} donated to "
                            f"{callee}() aliases persistent "
                            "self.* state — verify the cache "
                            "slot is overwritten before any "
                            "later read and suppress with a "
                            "justification",
                        )
                    )
        return out

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "donation",
        )
        return ctx.with_overrides(
            scan_files=[os.path.join(fixtures, "bad.py")]
        )

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "donation",
        )
        return ctx.with_overrides(
            scan_files=[os.path.join(fixtures, "clean.py")]
        )
