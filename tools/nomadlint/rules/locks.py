"""Lock discipline: static lock-acquisition graph over the
multi-threaded server/state/device modules.

The batch pipeline is speculative and multi-threaded (worker thread,
replay pool, warmup thread, supervisor probe thread, background
compile threads), and the GIL hides most interleavings from the CPU
tier-1 suite — so ordering bugs are checked statically, the way the
reference tree leans on ``go vet``/race CI.

Sub-checks:

* **lock-order** — build the acquired-while-holding graph: a ``with
  self._x_lock:`` (or ``.acquire()``) nested inside another held
  lock adds an edge, and calls made while holding a lock pull in the
  transitive lock set of the (module-set-resolved) callee.  Any
  cycle is a potential deadlock; a non-reentrant ``Lock`` nested
  inside itself is a guaranteed one.
* **lock-reinit** — replacing a lock object outside ``__init__``
  (``self._x_lock = threading.Lock()`` in a regular method) silently
  releases every queued waiter's mutual exclusion.  The supervisor
  failover path does this DELIBERATELY (abandoning a lock a wedged
  sacrificial thread may hold forever); every such deliberate skip
  needs an ``ALLOWLIST`` entry here carrying its justification, and
  stale entries (nothing matches anymore) are themselves findings so
  the allowlist can't rot.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, Rule, register

# (file basename, "Class.method", lock attr) -> one-line justification
ALLOWLIST: Dict[Tuple[str, str, str], str] = {
    (
        "batch_worker.py",
        "BatchWorker._on_device_transition",
        "_usage_cache_lock",
    ): (
        "documented wedge bypass: a sacrificial assemble thread may "
        "be parked inside _device_columns_locked holding the lock "
        "forever (device_put never returned); post-flip syncs must "
        "not queue behind it, and the stale-epoch cache key discards "
        "anything a late holder publishes"
    ),
}


@dataclass
class _LockInfo:
    key: str  # "<basename>:<Class>.<attr>"
    reentrant: bool
    cls: str
    attr: str


@dataclass
class _FnLocks:
    """Per-method lock facts for the interprocedural closure."""

    qualname: str  # "Class.method"
    path: str
    acquired: Set[str] = field(default_factory=set)
    # (outer_key, inner_key, line) direct nesting edges
    edges: List[Tuple[str, str, int]] = field(
        default_factory=list
    )
    # method names called while holding key: [(held, name, line)]
    held_calls: List[Tuple[str, str, int]] = field(
        default_factory=list
    )
    # method names called anywhere (for the transitive closure)
    calls: Set[str] = field(default_factory=set)


def _lock_attrs_of_class(
    cls: ast.ClassDef,
) -> Dict[str, bool]:
    """Lock attribute names assigned ``threading.Lock()`` /
    ``threading.RLock()`` anywhere in the class -> reentrant?"""
    out: Dict[str, bool] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (
            isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr in ("Lock", "RLock")
        ):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out[t.attr] = node.value.func.attr == "RLock"
    return out


def _self_lock_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodScanner:
    """Walks one method tracking the held-lock stack."""

    def __init__(
        self,
        fn: ast.FunctionDef,
        qualname: str,
        path: str,
        locks: Dict[str, _LockInfo],
    ) -> None:
        self.locks = locks
        self.out = _FnLocks(qualname=qualname, path=path)
        self._walk(fn, [])

    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        attr = _self_lock_attr(expr)
        if attr is not None and attr in self.locks:
            return self.locks[attr].key
        return None

    def _walk(self, node: ast.AST, held: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and child is not node:
                # nested defs run later on other threads; their
                # acquisitions are not nested under the current hold
                self._walk_fn_body(child)
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                keys: List[str] = []
                for item in child.items:
                    key = self._lock_key(item.context_expr)
                    if key is not None:
                        keys.append(key)
                        self._note_acquire(key, child.lineno, held)
                self._walk(child, held + keys)
                continue
            # explicit lock.acquire(): held until release or method
            # end (fixture support — live code uses `with`)
            if (
                isinstance(child, ast.Expr)
                and isinstance(child.value, ast.Call)
                and isinstance(child.value.func, ast.Attribute)
                and child.value.func.attr == "acquire"
            ):
                key = self._lock_key(child.value.func.value)
                if key is not None:
                    self._note_acquire(
                        key, child.lineno, held
                    )
                    held = held + [key]
                    continue
            if (
                isinstance(child, ast.Expr)
                and isinstance(child.value, ast.Call)
                and isinstance(child.value.func, ast.Attribute)
                and child.value.func.attr == "release"
            ):
                key = self._lock_key(child.value.func.value)
                if key is not None and key in held:
                    held = [k for k in held if k != key]
                    continue
            if isinstance(child, ast.Call):
                name = self._callee_name(child)
                if name:
                    self.out.calls.add(name)
                    for key in held:
                        self.out.held_calls.append(
                            (key, name, child.lineno)
                        )
            self._walk(child, held)

    def _walk_fn_body(self, fn: ast.FunctionDef) -> None:
        # nested function: scan with an empty hold stack but keep
        # recording its acquisitions/calls under this method's entry
        self._walk(fn, [])

    @staticmethod
    def _callee_name(call: ast.Call) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            return fn.attr
        if isinstance(fn, ast.Name):
            return fn.id
        return None

    def _note_acquire(
        self, key: str, line: int, held: List[str]
    ) -> None:
        self.out.acquired.add(key)
        for outer in held:
            self.out.edges.append((outer, key, line))


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "lock-order acyclicity + allowlisted lock replacement"
    )

    SCAN_KEYS = ("batch_worker", "plan_apply")

    def _files(self, ctx: Context) -> List[str]:
        override = ctx.overrides.get("scan_files")
        if override is not None:
            return list(override)
        files = [ctx.path(k) for k in self.SCAN_KEYS]
        for dir_key in ("state_dir", "device_dir"):
            root = ctx.path(dir_key)
            files.extend(
                os.path.join(root, fn)
                for fn in sorted(os.listdir(root))
                if fn.endswith(".py")
            )
        return files

    def check(self, ctx: Context) -> List[Finding]:
        files = self._files(ctx)
        locks: Dict[str, Dict[str, _LockInfo]] = {}
        classes: List[Tuple[str, ast.ClassDef]] = []
        for path in files:
            for node in ctx.tree(path).body:
                if isinstance(node, ast.ClassDef):
                    classes.append((path, node))
                    attrs = _lock_attrs_of_class(node)
                    base = os.path.basename(path)
                    locks[node.name] = {
                        attr: _LockInfo(
                            key=f"{base}:{node.name}.{attr}",
                            reentrant=reentrant,
                            cls=node.name,
                            attr=attr,
                        )
                        for attr, reentrant in attrs.items()
                    }

        # per-method scan
        fn_locks: Dict[str, List[_FnLocks]] = {}
        scanned: List[_FnLocks] = []
        reinits: List[Tuple[str, str, str, int]] = []
        for path, cls in classes:
            cls_locks = locks.get(cls.name, {})
            for node in cls.body:
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                qual = f"{cls.name}.{node.name}"
                scan = _MethodScanner(
                    node, qual, path, cls_locks
                ).out
                scanned.append(scan)
                fn_locks.setdefault(node.name, []).append(scan)
                if node.name != "__init__":
                    for sub in ast.walk(node):
                        if not isinstance(sub, ast.Assign):
                            continue
                        if not (
                            isinstance(sub.value, ast.Call)
                            and isinstance(
                                sub.value.func, ast.Attribute
                            )
                            and sub.value.func.attr
                            in ("Lock", "RLock")
                        ):
                            continue
                        for t in sub.targets:
                            attr = _self_lock_attr(t)
                            if attr is not None:
                                reinits.append(
                                    (path, qual, attr, sub.lineno)
                                )

        findings: List[Finding] = []

        # -- lock-reinit vs allowlist -----------------------------
        matched: Set[Tuple[str, str, str]] = set()
        for path, qual, attr, line in reinits:
            key = (os.path.basename(path), qual, attr)
            if key in ALLOWLIST:
                matched.add(key)
                continue
            findings.append(
                Finding(
                    self.name, path, line,
                    f"{qual} replaces lock {attr!r} outside "
                    "__init__ — waiters queued on the old object "
                    "lose mutual exclusion; if deliberate, add an "
                    "ALLOWLIST entry (tools/nomadlint/rules/"
                    "locks.py) with its justification",
                )
            )
        if "scan_files" not in ctx.overrides:
            for key, _why in ALLOWLIST.items():
                if key not in matched:
                    findings.append(
                        Finding(
                            self.name,
                            ctx.path("batch_worker"), 0,
                            f"stale lock-reinit ALLOWLIST entry "
                            f"{key!r}: no matching site exists — "
                            "remove it so the allowlist can't rot",
                        )
                    )

        # -- transitive lock closure per method -------------------
        def resolve(name: str) -> Optional[_FnLocks]:
            cands = fn_locks.get(name, [])
            return cands[0] if len(cands) == 1 else None

        closure: Dict[str, Set[str]] = {
            s.qualname: set(s.acquired) for s in scanned
        }
        by_qual = {s.qualname: s for s in scanned}
        changed = True
        while changed:
            changed = False
            for s in scanned:
                for name in s.calls:
                    callee = resolve(name)
                    if callee is None:
                        continue
                    add = closure[callee.qualname] - closure[
                        s.qualname
                    ]
                    if add:
                        closure[s.qualname] |= add
                        changed = True

        # -- edges: direct nesting + held calls -------------------
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        all_locks = {
            info.key: info
            for cls_map in locks.values()
            for info in cls_map.values()
        }
        for s in scanned:
            for outer, inner, line in s.edges:
                edges.setdefault(
                    (outer, inner), (s.path, line, s.qualname)
                )
            for held, name, line in s.held_calls:
                callee = resolve(name)
                if callee is None:
                    continue
                for inner in closure[callee.qualname]:
                    edges.setdefault(
                        (held, inner),
                        (s.path, line, s.qualname),
                    )

        # -- self-deadlock + cycles -------------------------------
        graph: Dict[str, Set[str]] = {}
        for (outer, inner), (path, line, qual) in edges.items():
            if outer == inner:
                info = all_locks.get(outer)
                if info is not None and not info.reentrant:
                    findings.append(
                        Finding(
                            self.name, path, line,
                            f"{qual} acquires non-reentrant lock "
                            f"{outer} while already holding it — "
                            "guaranteed self-deadlock",
                        )
                    )
                continue
            graph.setdefault(outer, set()).add(inner)

        for cycle in _cycles(graph):
            first = cycle[0]
            # anchor the finding on the edge closing the cycle
            path, line, qual = edges.get(
                (cycle[-1], first),
                edges.get((first, cycle[1 % len(cycle)]),
                          ("", 0, "?")),
            )
            findings.append(
                Finding(
                    self.name, path or self._files(ctx)[0], line,
                    "lock-order cycle (potential deadlock): "
                    + " -> ".join(cycle + [first])
                    + f" (closing edge in {qual})",
                )
            )
        return findings

    @classmethod
    def bad_fixture(cls, ctx, tmpdir):
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "locks",
        )
        return ctx.with_overrides(
            scan_files=[os.path.join(fixtures, "bad.py")]
        )

    @classmethod
    def clean_fixture(cls, ctx, tmpdir):
        fixtures = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "fixtures", "locks",
        )
        return ctx.with_overrides(
            scan_files=[os.path.join(fixtures, "clean.py")]
        )


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Distinct elementary cycles (each reported once, smallest
    rotation first) — Tarjan SCCs then one witness cycle per
    non-trivial component."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in graph.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    nodes = set(graph)
    for targets in graph.values():
        nodes |= targets
    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    # an SCC's node list is NOT a cycle path — report a witness
    # path whose every consecutive pair (and closing edge) is a
    # real edge, so the rendered lock order exists in the code
    return [_witness_cycle(comp, graph) for comp in sccs]


def _witness_cycle(
    comp: List[str], graph: Dict[str, Set[str]]
) -> List[str]:
    """One concrete elementary cycle inside a non-trivial SCC."""
    start = comp[0]
    compset = set(comp)
    dfs: List[Tuple[str, List[str]]] = [(start, [start])]
    while dfs:
        v, path = dfs.pop()
        for w in sorted(graph.get(v, ()), reverse=True):
            if w == start:
                return path
            if w in compset and w not in path:
                dfs.append((w, path + [w]))
    return comp  # unreachable: every SCC node lies on a cycle
