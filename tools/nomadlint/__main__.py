"""CLI runner: ``python -m tools.nomadlint``."""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import List

from .core import Context, all_rules, run

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def selfcheck(ctx: Context) -> int:
    """Every rule must trip on its bad fixture and stay quiet on its
    clean fixture — the framework's own acceptance gate."""
    rc = 0
    with tempfile.TemporaryDirectory() as tmp:
        for cls in all_rules():
            bad_ctx = cls.bad_fixture(ctx, tmp)
            tripped = [
                f
                for f in cls().check(bad_ctx)
                if f.rule == cls.name
            ]
            if not tripped:
                print(
                    f"SELFCHECK FAIL: rule {cls.name} did not "
                    "trip on its bad fixture",
                    file=sys.stderr,
                )
                rc = 1
            clean_ctx = cls.clean_fixture(ctx, tmp)
            quiet = cls().check(clean_ctx)
            if clean_ctx is not ctx and quiet:
                print(
                    f"SELFCHECK FAIL: rule {cls.name} tripped on "
                    f"its clean fixture: {quiet[0].message}",
                    file=sys.stderr,
                )
                rc = 1
            print(
                f"selfcheck {cls.name}: bad fixture -> "
                f"{len(tripped)} finding(s)"
            )
    return rc


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.nomadlint",
        description=(
            "pluggable AST static analysis for this repo "
            "(donation safety, jit purity, lock discipline, "
            "config/registry drift, stage accounting)"
        ),
    )
    parser.add_argument(
        "--repo", default=REPO, help="repo root to lint"
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule subset (default: all)",
    )
    parser.add_argument(
        "--files",
        nargs="+",
        help=(
            "restrict repo-wide rules to these files (single-file "
            "rules still read their fixed targets)"
        ),
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule inventory and exit",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="verify every rule trips its bad fixture",
    )
    parser.add_argument(
        "--dump-flowgraph", action="store_true",
        help=(
            "print the whole-program concurrency view (thread "
            "entries, lock table, shared attributes + guards)"
        ),
    )
    parser.add_argument(
        "--write-doc", action="store_true",
        help=(
            "regenerate the docs/ARCHITECTURE.md Concurrency-model "
            "section from the flowgraph (concurrency-doc rule "
            "enforces freshness)"
        ),
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in all_rules():
            print(f"{cls.name:24s} {cls.description}")
        return 0

    if args.dump_flowgraph or args.write_doc:
        from .flowgraph import flowgraph, render_dump

        ctx = Context(args.repo)
        dump = render_dump(flowgraph(ctx), ctx.repo)
        if args.dump_flowgraph:
            print(dump)
        if args.write_doc:
            from .rules.concurrency_doc import (
                MARK_BEGIN,
                MARK_END,
            )

            doc_path = ctx.path("arch_doc")
            with open(doc_path) as fh:
                doc = fh.read()
            if MARK_BEGIN not in doc or MARK_END not in doc:
                print(
                    "docs/ARCHITECTURE.md has no flowgraph "
                    f"markers ({MARK_BEGIN!r}); add a Concurrency "
                    "model section with begin/end markers first",
                    file=sys.stderr,
                )
                return 2
            head, rest = doc.split(MARK_BEGIN, 1)
            _stale, tail = rest.split(MARK_END, 1)
            with open(doc_path, "w") as fh:
                fh.write(
                    head
                    + MARK_BEGIN
                    + "\n\n"
                    + dump.strip()
                    + "\n\n"
                    + MARK_END
                    + tail
                )
            print(f"wrote flowgraph section to {doc_path}")
        return 0

    overrides = {}
    if args.files:
        # CLI narrowing is "narrow_files", NOT the fixtures'
        # "scan_files": cross-file rules (config-drift, the
        # flowgraph rules) declare file dependencies spanning the
        # repo and always run against the full set — a narrowed run
        # must not false-pass by hiding one side of a pair
        overrides["narrow_files"] = [
            os.path.abspath(f) for f in args.files
        ]
    ctx = Context(args.repo, overrides)

    if args.selfcheck:
        return selfcheck(Context(args.repo))

    rule_names = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        result = run(ctx, rule_names)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 2

    if args.as_json:
        print(
            json.dumps(
                {
                    "ok": result.ok,
                    "rules_run": result.rules_run,
                    "findings": [
                        f.to_dict(ctx.repo)
                        for f in result.findings
                    ],
                    "suppressed": [
                        f.to_dict(ctx.repo)
                        for f in result.suppressed
                    ],
                },
                indent=2,
            )
        )
    else:
        for f in result.findings:
            print(f.render(ctx.repo), file=sys.stderr)
        print(
            f"nomadlint: {len(result.rules_run)} rule(s), "
            f"{len(result.findings)} finding(s), "
            f"{len(result.suppressed)} suppressed"
        )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
