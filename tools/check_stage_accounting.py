#!/usr/bin/env python3
"""Compatibility shim over ``tools/nomadlint``.

The 11 stage-accounting checks that used to live here as a 608-line
monolith are now individual rules in the pluggable AST analysis suite
(``tools/nomadlint/rules/stage_accounting.py`` — run them with
``python -m tools.nomadlint``, which also carries the newer donation-
safety / jit-purity / lock-discipline / config-drift passes).

This module keeps the original surface — the path globals, the AST
helpers and ``check() -> (ok, [problem strings])`` — so
``tests/test_stage_accounting.py`` and operator muscle memory keep
working unmodified.  The path globals are read at call time: tests
monkeypatch them to point single files at mutated copies, and
``check()`` forwards them as nomadlint Context overrides.
"""
from __future__ import annotations

import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.nomadlint import astutil as _astutil  # noqa: E402
from tools.nomadlint.core import Context, run  # noqa: E402
from tools.nomadlint.rules import MIGRATED_RULES  # noqa: E402

BATCH_WORKER = os.path.join(
    REPO, "nomad_tpu", "server", "batch_worker.py"
)
PLAN_APPLY = os.path.join(
    REPO, "nomad_tpu", "server", "plan_apply.py"
)
TRACE_MOD = os.path.join(REPO, "nomad_tpu", "trace.py")
BENCH = os.path.join(REPO, "bench.py")
DEVICE_DIR = os.path.join(REPO, "nomad_tpu", "device")
DEVICE_SUPERVISOR = os.path.join(DEVICE_DIR, "supervisor.py")
CLI = os.path.join(REPO, "nomad_tpu", "cli.py")
EXPLAIN_MOD = os.path.join(REPO, "nomad_tpu", "explain.py")
TPU_STACK = os.path.join(REPO, "nomad_tpu", "sched", "tpu_stack.py")
FEASIBLE = os.path.join(REPO, "nomad_tpu", "sched", "feasible.py")
SERVER_MOD = os.path.join(REPO, "nomad_tpu", "server", "server.py")

# historical helper API, re-exported from the nomadlint toolbox
_parse = _astutil.parse
timings_keys = _astutil.timings_keys
observed_keys = _astutil.observed_keys
span_names_used = _astutil.span_names_used
span_registry = _astutil.span_registry


def _context() -> Context:
    """Context bound to this module's (possibly monkeypatched) path
    globals."""
    return Context(
        REPO,
        overrides={
            "batch_worker": BATCH_WORKER,
            "plan_apply": PLAN_APPLY,
            "trace": TRACE_MOD,
            "bench": BENCH,
            "device_dir": DEVICE_DIR,
            "device_supervisor": DEVICE_SUPERVISOR,
            "cli": CLI,
            "explain": EXPLAIN_MOD,
            "tpu_stack": TPU_STACK,
            "feasible": FEASIBLE,
            "server": SERVER_MOD,
        },
    )


def check() -> Tuple[bool, List[str]]:
    """Run the 11 migrated stage-accounting rules; returns
    ``(ok, [problem strings])`` like the historical monolith."""
    result = run(_context(), MIGRATED_RULES)
    problems = [f.message for f in result.findings]
    return not problems, problems


def main() -> int:
    ok, problems = check()
    if ok:
        print("stage accounting: OK")
        return 0
    for p in problems:
        print(f"stage accounting: {p}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
