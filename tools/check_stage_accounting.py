#!/usr/bin/env python3
"""Stage-accounting lint: every pipeline stage the BatchWorker tracks
must actually be observed and must flow into the bench output.

Guards the invariant that keeps per-stage time attributable across
rounds (a new stage added to ``BatchWorker.timings`` without an
``_observe`` call, or a bench that stops exporting the timings dict
wholesale, would silently vanish from BENCH_*.json and /v1/metrics):

1. every key in the ``self.timings = {...}`` literal in
   ``nomad_tpu/server/batch_worker.py`` appears in at least one
   ``self._observe("<key>", ...)`` call;
2. every ``self._observe("<key>", ...)`` call uses a declared key
   (no orphan stages accumulating into nothing);
3. ``bench.py`` builds its stage times from ``worker.timings``
   wholesale (``dict(worker.timings)``) and exports them under the
   ``e2e_stage_times_s`` JSON key, so new stages flow through without
   a bench edit;
4. every flight-recorder span/event name used in
   ``batch_worker.py`` and ``plan_apply.py`` (``TRACE.span(...)``,
   ``TRACE.add_span(...)``, ``TRACE.event(...)``) is declared in the
   ``SPAN_NAMES`` registry in ``nomad_tpu/trace.py`` — a renamed
   stage must update the documented registry (and with it every
   dashboard/report keyed on the name), never drift silently;
5. every span/event name used by the accelerator supervisor
   (``nomad_tpu/device/*.py``) is declared in ``SPAN_NAMES`` too, and
   every ``device.*`` counter/gauge/sample it emits appears in the
   ``METRIC_COUNTERS``/``METRIC_GAUGES``/``METRIC_SAMPLES`` registry
   literals in ``device/supervisor.py`` — those are zero-registered
   at supervisor construction, which is what guarantees
   ``prometheus_text()`` exports the whole ``device.*`` family before
   the first incident;
6. the operator debug bundle (``cli.py`` ``cmd_operator_debug``)
   captures ``/v1/device``, so a bundle from a degraded server always
   carries the supervisor's state history;
7. placement explainability (``nomad_tpu/explain.py``): every
   ``placement.*`` metric name emitted is zero-registered — literal
   names must appear in the ``PLACEMENT_COUNTERS``/
   ``PLACEMENT_GAUGES`` registries, and f-string emissions may only
   interpolate through the fixed ``reason_slug``/``dimension_slug``
   vocabularies — and the server zero-registers the family at
   construction;
8. the vectorized path's filter-reason strings come from the shared
   serial-chain constants: a string literal passed to
   ``filter_node(...)`` in ``sched/tpu_stack.py`` must be one of the
   ``FILTER_*`` constants' values (``sched/feasible.py``), and a
   literal ``exhausted_node(...)`` dimension must be in the
   ``allocs_fit`` superset vocabulary — ad-hoc strings would silently
   drift from the serial path's vocabulary (and from the
   ``placement.filtered.<slug>`` counter families keyed on it);
9. the operator debug bundle captures ``/v1/placements`` so the
   per-eval explanations travel with the traces they cross-reference;
10. continuous micro-batching observability: the
    ``batch_worker.admit`` span (and ``batch_worker.admit_deferred``
    event) are declared in ``SPAN_NAMES``, and every ``admission.*``
    counter the worker emits (literal first args of
    ``incr/set_gauge/add_sample`` plus the ``self._count_admission(
    "<kind>")`` call sites, which emit ``admission.<kind>``) appears
    in the ``ADMISSION_COUNTERS`` registry literal in
    ``batch_worker.py`` — which ``server.py`` zero-registers at
    construction, so prometheus scrapes export the family before the
    first mid-chain admission;
11. bench.py exports the ``latency_sweep`` JSON block (offered-load
    vs p50/p99 with p99 trace exemplars) — the per-round tracking of
    the <250 ms tail-latency target.

Run directly (exits non-zero on violation) or via the tier-1 test in
``tests/test_stage_accounting.py``.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCH_WORKER = os.path.join(
    REPO, "nomad_tpu", "server", "batch_worker.py"
)
PLAN_APPLY = os.path.join(
    REPO, "nomad_tpu", "server", "plan_apply.py"
)
TRACE_MOD = os.path.join(REPO, "nomad_tpu", "trace.py")
BENCH = os.path.join(REPO, "bench.py")
DEVICE_DIR = os.path.join(REPO, "nomad_tpu", "device")
DEVICE_SUPERVISOR = os.path.join(DEVICE_DIR, "supervisor.py")
CLI = os.path.join(REPO, "nomad_tpu", "cli.py")
EXPLAIN_MOD = os.path.join(REPO, "nomad_tpu", "explain.py")
TPU_STACK = os.path.join(REPO, "nomad_tpu", "sched", "tpu_stack.py")
FEASIBLE = os.path.join(REPO, "nomad_tpu", "sched", "feasible.py")
SERVER_MOD = os.path.join(REPO, "nomad_tpu", "server", "server.py")

# allocs_fit / BinPackIterator exhaustion-dimension vocabulary a
# literal exhausted_node() in the vectorized path may use
EXHAUST_DIMENSIONS = {"cpu", "memory", "disk"}

# the trace-recording call surface (nomad_tpu/trace.py Tracer)
_TRACE_CALLS = {"span", "add_span", "event"}


def _parse(path: str) -> ast.AST:
    with open(path) as fh:
        return ast.parse(fh.read(), filename=path)


def timings_keys(tree: ast.AST) -> Set[str]:
    """Keys of the ``self.timings = {...}`` dict literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Attribute)
                and target.attr == "timings"
                and isinstance(node.value, ast.Dict)
            ):
                return {
                    k.value
                    for k in node.value.keys
                    if isinstance(k, ast.Constant)
                }
    return set()


def observed_keys(tree: ast.AST) -> Set[str]:
    """First-arg string constants of every ``._observe(...)`` call
    (``._observe_chunk`` delegates its stage key to ``_observe``, so
    its call sites count too)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("_observe", "_observe_chunk")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.add(node.args[0].value)
    return out


def span_names_used(tree: ast.AST) -> Set[str]:
    """Span/event name literals passed to ``.span/.add_span/.event``
    calls.  The name is the first *string-constant* positional (the
    leading positional is the eval-id expression, never a literal).
    ``._observe_chunk("<stage>", ...)`` emits its span name as
    f"batch_worker.{stage}" — a non-constant the AST scan can't see —
    so its stage constants count as that derived name here."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if (
            node.func.attr == "_observe_chunk"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.add(f"batch_worker.{node.args[0].value}")
            continue
        if node.func.attr not in _TRACE_CALLS:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                out.add(arg.value)
                break
    return out


def span_registry(tree: ast.AST) -> Set[str]:
    """String constants inside the ``SPAN_NAMES = frozenset({...})``
    assignment in nomad_tpu/trace.py."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Name)
                and target.id == "SPAN_NAMES"
            ):
                return {
                    n.value
                    for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
    return set()


def device_metric_names(tree: ast.AST) -> Set[str]:
    """``device.*`` metric-name literals emitted anywhere in a device
    module: first string-constant positional of ``.incr(...)``,
    ``.set_gauge(...)`` or ``.add_sample(...)`` calls."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("incr", "set_gauge", "add_sample")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("device.")
        ):
            out.add(node.args[0].value)
    return out


def device_metric_registry(tree: ast.AST) -> Set[str]:
    """String constants inside the ``METRIC_COUNTERS`` /
    ``METRIC_GAUGES`` / ``METRIC_SAMPLES`` frozenset literals in
    device/supervisor.py (the names zero-registered at supervisor
    construction, hence always present in ``prometheus_text()``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id in (
                "METRIC_COUNTERS",
                "METRIC_GAUGES",
                "METRIC_SAMPLES",
            ):
                out |= {
                    n.value
                    for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
    return out


def _device_module_paths() -> List[str]:
    return sorted(
        os.path.join(DEVICE_DIR, name)
        for name in os.listdir(DEVICE_DIR)
        if name.endswith(".py")
    )


def _registry_tuple_names(tree: ast.AST, target_name: str) -> Set[str]:
    """String constants reachable inside a module-level assignment
    (handles the PLACEMENT_COUNTERS tuple-of-f-strings construction by
    collecting the slug tuples it references too — callers pass the
    pre-joined prefix checks separately)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Name)
                and target.id == target_name
            ):
                return {
                    n.value
                    for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
    return set()


def placement_metric_problems() -> List[str]:
    """Check 7: placement.* emissions in explain.py stay inside the
    zero-registered families.  Literal names must be registered
    verbatim; f-string names may only be `placement.filtered.{...}` /
    `placement.exhausted.{...}` with the slug produced by
    reason_slug()/dimension_slug() (the fixed vocabularies)."""
    problems: List[str] = []
    tree = _parse(EXPLAIN_MOD)
    counters = _registry_tuple_names(tree, "PLACEMENT_COUNTERS")
    gauges = _registry_tuple_names(tree, "PLACEMENT_GAUGES")
    filter_slugs = _registry_tuple_names(
        tree, "PLACEMENT_FILTER_SLUGS"
    )
    exhaust_slugs = _registry_tuple_names(
        tree, "PLACEMENT_EXHAUST_SLUGS"
    )
    if not (counters and gauges and filter_slugs and exhaust_slugs):
        return [
            "could not find the PLACEMENT_* registries in "
            "nomad_tpu/explain.py"
        ]
    registered = (
        counters
        | gauges
        | {f"placement.filtered.{s}" for s in filter_slugs}
        | {f"placement.exhausted.{s}" for s in exhaust_slugs}
    )
    slug_fns = {"reason_slug", "dimension_slug"}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("incr", "set_gauge", "add_sample")
            and node.args
        ):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(
            arg.value, str
        ):
            if arg.value.startswith("placement.") and (
                arg.value not in registered
            ):
                problems.append(
                    f"placement metric {arg.value!r} emitted but not "
                    "in the zero-registered PLACEMENT_* registries"
                )
            continue
        if isinstance(arg, ast.JoinedStr):
            prefix = ""
            if arg.values and isinstance(arg.values[0], ast.Constant):
                prefix = str(arg.values[0].value)
            if not prefix.startswith("placement."):
                continue
            if prefix not in (
                "placement.filtered.",
                "placement.exhausted.",
            ):
                problems.append(
                    f"dynamic placement metric prefix {prefix!r} has "
                    "no zero-registered family"
                )
                continue
            for part in arg.values[1:]:
                if not isinstance(part, ast.FormattedValue):
                    continue
                call = part.value
                ok = (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)
                    and call.func.id in slug_fns
                )
                if not ok:
                    problems.append(
                        f"placement metric family {prefix!r} "
                        "interpolates a value not produced by "
                        "reason_slug()/dimension_slug() — the name "
                        "space would be unbounded"
                    )
    with open(SERVER_MOD) as fh:
        server_src = fh.read()
    if "preregister" not in server_src or "explain" not in server_src:
        problems.append(
            "server.py no longer zero-registers the placement.* "
            "families at construction (explain.preregister)"
        )
    return problems


def reason_vocabulary_problems() -> List[str]:
    """Check 8: reason-string literals used by the vectorized path
    must come from the serial chain's shared vocabulary."""
    problems: List[str] = []
    feasible_tree = _parse(FEASIBLE)
    allowed: Set[str] = set()
    for node in ast.walk(feasible_tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Name)
                and target.id.startswith("FILTER_")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                allowed.add(node.value.value)
    if not allowed:
        return [
            "could not find the FILTER_* reason constants in "
            "sched/feasible.py"
        ]
    for node in ast.walk(_parse(TPU_STACK)):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        ):
            continue
        literal = node.args[1].value
        if node.func.attr == "filter_node" and literal not in allowed:
            problems.append(
                "ad-hoc filter reason literal in sched/tpu_stack.py: "
                f"{literal!r} is not a shared FILTER_* constant value "
                "(import the constant instead)"
            )
        if (
            node.func.attr == "exhausted_node"
            and literal not in EXHAUST_DIMENSIONS
        ):
            problems.append(
                "ad-hoc exhaustion dimension literal in "
                f"sched/tpu_stack.py: {literal!r} is outside the "
                "allocs_fit superset vocabulary"
            )
    return problems


def admission_metric_problems(bw_tree: ast.AST) -> List[str]:
    """Check 10 (counter half): every ``admission.*`` metric the
    batch worker emits is in the zero-registered ADMISSION_COUNTERS
    registry, and server.py actually zero-registers it."""
    problems: List[str] = []
    registry = _registry_tuple_names(bw_tree, "ADMISSION_COUNTERS")
    if not registry:
        return [
            "could not find the ADMISSION_COUNTERS registry in "
            "batch_worker.py"
        ]
    emitted: Set[str] = set()
    for node in ast.walk(bw_tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
        ):
            continue
        if (
            node.func.attr in ("incr", "set_gauge", "add_sample")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("admission.")
        ):
            emitted.add(node.args[0].value)
        # _count_admission("<kind>") emits admission.<kind>
        if (
            node.func.attr == "_count_admission"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            emitted.add(f"admission.{node.args[0].value}")
    unregistered = emitted - registry
    if unregistered:
        problems.append(
            "admission.* metrics emitted but not in the "
            "ADMISSION_COUNTERS registry (they would be absent from "
            "prometheus scrapes until the first mid-chain "
            f"admission): {sorted(unregistered)}"
        )
    with open(SERVER_MOD) as fh:
        server_src = fh.read()
    if "ADMISSION_COUNTERS" not in server_src:
        problems.append(
            "server.py no longer zero-registers the admission.* "
            "family at construction (ADMISSION_COUNTERS preregister)"
        )
    return problems


def bench_exports_timings(tree: ast.AST, source: str) -> List[str]:
    """Problems with bench.py's stage export (empty list = ok)."""
    problems = []
    wholesale = any(
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "dict"
        and node.args
        and isinstance(node.args[0], ast.Attribute)
        and node.args[0].attr == "timings"
        for node in ast.walk(tree)
    )
    if not wholesale:
        problems.append(
            "bench.py no longer snapshots the stage times wholesale "
            "(expected a dict(worker.timings) call) — new stages "
            "would silently drop from the bench"
        )
    if '"e2e_stage_times_s"' not in source:
        problems.append(
            "bench.py no longer exports the e2e_stage_times_s JSON key"
        )
    # check 11: the paced-arrival latency sweep must keep flowing into
    # BENCH json (the per-round tail-latency tracking)
    if '"latency_sweep"' not in source:
        problems.append(
            "bench.py no longer exports the latency_sweep JSON block "
            "(offered-load vs p50/p99 with p99 trace exemplars)"
        )
    return problems


def check() -> Tuple[bool, List[str]]:
    problems: List[str] = []
    bw_tree = _parse(BATCH_WORKER)
    declared = timings_keys(bw_tree)
    observed = observed_keys(bw_tree)
    if not declared:
        problems.append(
            "could not find the self.timings literal in "
            "batch_worker.py"
        )
    unobserved = declared - observed
    if unobserved:
        problems.append(
            "timings keys never passed to _observe "
            f"(stage time would stay 0 forever): {sorted(unobserved)}"
        )
    orphans = observed - declared
    if orphans:
        problems.append(
            "_observe calls with keys missing from the timings "
            f"literal (would KeyError at runtime): {sorted(orphans)}"
        )
    registry = span_registry(_parse(TRACE_MOD))
    if not registry:
        problems.append(
            "could not find the SPAN_NAMES registry in "
            "nomad_tpu/trace.py"
        )
    used = span_names_used(bw_tree) | span_names_used(
        _parse(PLAN_APPLY)
    )
    unregistered = used - registry
    if unregistered:
        problems.append(
            "span names used but missing from trace.SPAN_NAMES "
            "(rename must update the documented registry): "
            f"{sorted(unregistered)}"
        )
    # check 10 (span half): the continuous micro-batching admission
    # stage must stay a registered, documented span name even if its
    # call sites change shape
    for required in (
        "batch_worker.admit",
        "batch_worker.admit_deferred",
    ):
        if required not in registry:
            problems.append(
                f"{required!r} missing from trace.SPAN_NAMES — the "
                "mid-chain admission stage would vanish from every "
                "trace-keyed dashboard"
            )
    # accelerator supervisor: span names registered, device.* metrics
    # zero-registered (so prometheus_text() always exports them)
    device_spans: Set[str] = set()
    device_metrics: Set[str] = set()
    for path in _device_module_paths():
        tree = _parse(path)
        device_spans |= span_names_used(tree)
        device_metrics |= device_metric_names(tree)
    unregistered = device_spans - registry
    if unregistered:
        problems.append(
            "device-supervisor span names missing from "
            f"trace.SPAN_NAMES: {sorted(unregistered)}"
        )
    metric_registry = device_metric_registry(
        _parse(DEVICE_SUPERVISOR)
    )
    if not metric_registry:
        problems.append(
            "could not find the METRIC_COUNTERS/GAUGES/SAMPLES "
            "registry in device/supervisor.py"
        )
    unexported = device_metrics - metric_registry
    if unexported:
        problems.append(
            "device.* metrics emitted but not in the supervisor's "
            "zero-registered registry (they would be absent from "
            f"prometheus_text() until the first incident): "
            f"{sorted(unexported)}"
        )
    with open(CLI) as fh:
        cli_src = fh.read()
    bundle_src = cli_src.split("cmd_operator_debug", 1)[-1].split(
        "def ", 1
    )[0]
    if '"/v1/device"' not in bundle_src:
        problems.append(
            "the operator debug bundle (cli.cmd_operator_debug) no "
            "longer captures /v1/device"
        )
    if "/v1/placements" not in bundle_src:
        problems.append(
            "the operator debug bundle (cli.cmd_operator_debug) no "
            "longer captures /v1/placements"
        )
    problems.extend(placement_metric_problems())
    problems.extend(reason_vocabulary_problems())
    problems.extend(admission_metric_problems(bw_tree))
    with open(BENCH) as fh:
        bench_src = fh.read()
    problems.extend(bench_exports_timings(ast.parse(bench_src), bench_src))
    return not problems, problems


def main() -> int:
    ok, problems = check()
    if ok:
        print("stage accounting: OK")
        return 0
    for p in problems:
        print(f"stage accounting: {p}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
