#!/usr/bin/env python3
"""Render eval flight-recorder traces as indented terminal waterfalls.

Input is the JSON the server serves at ``/v1/traces/<eval_id>`` (one
trace), ``/v1/traces?full=1`` (a list), or the cluster-scope
``/v1/cluster/traces[/<ref>]`` fan-in shapes.  Sources: an HTTP(S)
URL, a file path, or ``-`` for stdin.

    python tools/trace_report.py http://127.0.0.1:4646/v1/traces/abc123
    python tools/trace_report.py 'http://127.0.0.1:4646/v1/traces?full=1&slow_ms=50'
    curl -s .../v1/cluster/traces/abc123 | python tools/trace_report.py -

Output per trace: a header line (eval id, outcome, total duration,
span/drop counts) and one row per span — offset from the trace root,
a per-server lane tag, a depth-indented name, the span duration, a
proportional bar, and the non-default attributes — so a slow eval
reads as a waterfall:

    trace 53a1b2#7 outcome=speculative 12.41ms spans=12
        0.00ms  [leader  ]  broker.dequeue            0.00ms  queue=service
        0.21ms  [server-1]  batch_worker.simulate     1.20ms  ==
        ...

Stitched cross-server traces get one lane per ``server_id``: spans a
follower recorded and shipped back carry that follower's id in the
lane column, spans the serving server recorded itself show in the
``leader`` lane.  Remote segments are re-anchored onto the leader's
clock via wall-time deltas, so a span that lands before the trace
root or past its end is flagged ``CLOCK-SKEW?`` rather than silently
reordered — the gap is real evidence of clock disagreement between
the two servers, not of time travel.
"""
from __future__ import annotations

import json
import sys
from typing import Dict, List

BAR_WIDTH = 24
# remote segments are wall-clock re-anchored; offsets outside the
# trace's own [0, total] envelope by more than this many ms are
# flagged as clock-skew suspects instead of being trusted
SKEW_EPS_MS = 0.05


def _load(source: str):
    if source == "-":
        return json.load(sys.stdin)
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(source) as resp:  # noqa: S310 — operator tool
            return json.loads(resp.read())
    with open(source) as fh:
        return json.load(fh)


def _depths(spans: List[Dict]) -> Dict[int, int]:
    by_id = {s["id"]: s for s in spans}
    depths: Dict[int, int] = {}

    def depth(sid: int) -> int:
        if sid in depths:
            return depths[sid]
        parent = by_id[sid].get("parent")
        d = 0 if parent is None or parent not in by_id else (
            depth(parent) + 1
        )
        depths[sid] = d
        return d

    for s in spans:
        depth(s["id"])
    return depths


def _fmt_attrs(attrs: Dict) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))


def _lane(span: Dict, local: str) -> str:
    return (span.get("attrs") or {}).get("server_id") or local


def _skew_suspect(span: Dict, total) -> bool:
    off = span.get("off_ms", 0.0)
    if off < -SKEW_EPS_MS:
        return True
    dur = span.get("dur_ms")
    if total is not None and dur is not None:
        return off + dur > total + SKEW_EPS_MS
    return False


def render_trace(trace: Dict) -> str:
    """One trace -> waterfall text (no trailing newline)."""
    spans = sorted(trace.get("spans") or [], key=lambda s: s["off_ms"])
    total = trace.get("duration_ms")
    # lane name for spans the serving server recorded itself: the
    # cluster endpoint stamps the winning server as "server"
    local = trace.get("server") or "leader"
    lanes = {_lane(s, local) for s in spans}
    multi_lane = len(lanes) > 1
    skew = sum(1 for s in spans if _skew_suspect(s, total))
    header = (
        f"trace {trace.get('trace_id', trace.get('eval_id', '?'))} "
        f"outcome={trace.get('outcome')} "
        + (f"{total:.2f}ms " if total is not None else "(in flight) ")
        + f"spans={len(spans)}"
    )
    if multi_lane:
        header += f" servers={len(lanes)}"
    if trace.get("dropped"):
        header += f" dropped={trace['dropped']}"
    if trace.get("orphans"):
        header += f" ORPHANS={trace['orphans']}"
    if skew:
        header += f" CLOCK-SKEW-SUSPECT={skew}"
    if trace.get("servers"):
        # cluster fan-in pick: which peers answered the query
        reach = trace["servers"]
        bad = sorted(a for a, st in reach.items() if st != "ok")
        header += f"\n  fan-in: asked={len(reach)}" + (
            f" unreachable={','.join(bad)}" if bad else ""
        )
    if trace.get("attrs"):
        header += "\n  " + _fmt_attrs(trace["attrs"])
    lines = [header]
    depths = _depths(spans)
    name_w = max(
        (len(s["name"]) + 2 * depths[s["id"]] for s in spans),
        default=0,
    )
    lane_w = max((len(lane) for lane in lanes), default=0)
    scale = total if total else 1.0
    for s in spans:
        dur = s.get("dur_ms")
        bar = ""
        if dur and scale:
            bar = "=" * max(1, round(dur / scale * BAR_WIDTH))
        name = "  " * depths[s["id"]] + s["name"]
        dur_txt = f"{dur:.2f}ms" if dur is not None else "OPEN"
        lane_txt = (
            f"[{_lane(s, local):<{lane_w}}]  " if multi_lane else ""
        )
        row = (
            f"  {s['off_ms']:9.2f}ms  {lane_txt}{name:<{name_w}}  "
            f"{dur_txt:>10}  {bar:<{BAR_WIDTH}}"
        )
        extras = dict(s.get("attrs") or {})
        if multi_lane:
            extras.pop("server_id", None)  # shown as the lane tag
        if s.get("thread"):
            extras["thread"] = s["thread"]
        if extras:
            row += f"  {_fmt_attrs(extras)}"
        if _skew_suspect(s, total):
            row = row.rstrip() + "  CLOCK-SKEW?"
        lines.append(row.rstrip())
    return "\n".join(lines)


def render(payload) -> str:
    """A trace dict or a list of them (summaries allowed) -> text."""
    if isinstance(payload, dict) and isinstance(
        payload.get("traces"), list
    ):
        # /v1/cluster/traces fan-in envelope: unwrap, keep the
        # per-server reachability as a trailer
        parts = [render(payload["traces"])]
        reach = payload.get("servers") or {}
        bad = sorted(a for a, st in reach.items() if st != "ok")
        if reach:
            parts.append(
                f"fan-in: asked={len(reach)}"
                + (f" unreachable={','.join(bad)}" if bad else "")
            )
        return "\n\n".join(p for p in parts if p)
    if isinstance(payload, list):
        parts = []
        for entry in payload:
            if isinstance(entry.get("spans"), list):
                parts.append(render_trace(entry))
            else:
                # listing without ?full=1: summaries only
                dur = entry.get("duration_ms")
                where = (
                    f" server={entry['server']}"
                    if entry.get("server")
                    else ""
                )
                parts.append(
                    f"trace {entry.get('trace_id')} "
                    f"outcome={entry.get('outcome')} "
                    + (
                        f"{dur:.2f}ms "
                        if dur is not None
                        else "(in flight) "
                    )
                    + f"spans={entry.get('spans')}"
                    + where
                    + " (fetch /v1/traces/<eval_id> for the waterfall)"
                )
        return "\n\n".join(parts)
    return render_trace(payload)


def main(argv: List[str]) -> int:
    if len(argv) != 2 or argv[1] in ("-h", "--help"):
        print(__doc__, file=sys.stderr)
        return 2
    print(render(_load(argv[1])))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
