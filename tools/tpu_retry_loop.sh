#!/bin/bash
# Round-long TPU recovery loop (VERDICT r4 item #1): the tunnel session
# has been wedged since round 3; stale sessions expire on their own
# schedule, so a single 600s preflight at bench time keeps missing the
# window.  This loop retries a bounded bench attempt periodically for
# the whole round, logs every attempt, and stops on the first success.
#
# Single-process discipline: each attempt runs bench.py which takes the
# cross-process flock (nomad_tpu/device_lock.py) before backend init,
# so an attempt can never overlap the driver's end-of-round bench run.
set -u
cd /root/repo
LOG=bench_attempts_r05.log
OUT=BENCH_r05_attempt.json
SLEEP_S=${TPU_RETRY_SLEEP_S:-1500}
PREFLIGHT_S=${TPU_RETRY_PREFLIGHT_S:-240}
n=0
while true; do
  n=$((n + 1))
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  echo "[$ts] attempt $n: starting (preflight ${PREFLIGHT_S}s)" >> "$LOG"
  BENCH_PREFLIGHT_S=$PREFLIGHT_S NOMAD_TPU_DEVICE_LOCK_WAIT=120 \
    timeout 3600 python bench.py > /tmp/bench_try.out 2> /tmp/bench_try.err
  rc=$?
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  # first matching diagnostic, NOT the raw tail — bench.py echoes this
  # very log on failure and recording that would nest it recursively
  tail_line=$(grep -m1 -E "unreachable|preflight: fatal|device ok"     /tmp/bench_try.err 2>/dev/null | head -c 160)
  echo "[$ts] attempt $n: rc=$rc ${tail_line}" >> "$LOG"
  if [ $rc -eq 0 ]; then
    cp /tmp/bench_try.out "$OUT"
    echo "[$ts] attempt $n: SUCCESS — result saved to $OUT" >> "$LOG"
    exit 0
  fi
  sleep "$SLEEP_S"
done
