#!/bin/bash
# DEPRECATED thin wrapper — the in-process DeviceSupervisor
# (nomad_tpu/device) now owns accelerator recovery: servers detect a
# wedged device via canary probes + launch watchdogs and hot-fail over
# to the CPU backend without any external loop.  This script remains
# only for unattended round-long bench retries, and delegates every
# health decision to the supervisor's preflight
# (`python -m nomad_tpu.device.preflight`); each attempt's
# machine-readable DEVICE_PREFLIGHT state line lands in the log.
#
# Single-process discipline: the preflight and bench.py both take the
# cross-process flock (nomad_tpu/device_lock.py) before backend init,
# so an attempt can never overlap the driver's end-of-round bench run.
set -u
cd /root/repo
LOG=bench_attempts_r06.log
OUT=BENCH_r06_attempt.json
SLEEP_S=${TPU_RETRY_SLEEP_S:-1500}
PREFLIGHT_S=${TPU_RETRY_PREFLIGHT_S:-240}
n=0
while true; do
  n=$((n + 1))
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  echo "[$ts] attempt $n: preflight (budget ${PREFLIGHT_S}s)" >> "$LOG"
  NOMAD_TPU_PREFLIGHT_S=$PREFLIGHT_S NOMAD_TPU_DEVICE_LOCK_WAIT=120 \
    timeout $((PREFLIGHT_S + 180)) python -m nomad_tpu.device.preflight \
    > /tmp/preflight_try.out 2> /tmp/preflight_try.err
  pf_rc=$?
  state_line=$(grep -m1 '^DEVICE_PREFLIGHT' /tmp/preflight_try.out | head -c 400)
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  echo "[$ts] attempt $n: ${state_line:-DEVICE_PREFLIGHT (no output, rc=$pf_rc)}" >> "$LOG"
  # the exit code is the contract (0 = HEALTHY or SKIPPED may proceed);
  # the state line is for the log, not for parsing
  if [ $pf_rc -eq 0 ]; then
    # device answered: run the bench with a short residual preflight
    BENCH_PREFLIGHT_S=60 NOMAD_TPU_DEVICE_LOCK_WAIT=120 \
      timeout 3600 python bench.py > /tmp/bench_try.out 2> /tmp/bench_try.err
    rc=$?
    ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    tail_line=$(grep -m1 -E "unreachable|preflight: fatal|device ok" /tmp/bench_try.err 2>/dev/null | head -c 160)
    echo "[$ts] attempt $n: bench rc=$rc ${tail_line}" >> "$LOG"
    if [ $rc -eq 0 ]; then
      cp /tmp/bench_try.out "$OUT"
      echo "[$ts] attempt $n: SUCCESS — result saved to $OUT" >> "$LOG"
      exit 0
    fi
  fi
  sleep "$SLEEP_S"
done
