"""Benchmark: placements/sec on a simulated 10k-node / 100k-alloc cluster
(BASELINE.json config family; binpack service placements).

Compares three backends on identical evaluation streams:
  * oracle   — the host iterator chain with reference semantics
               (the "stock binpack" baseline);
  * tpu-sel  — the per-placement vectorized kernel behind the full
               scheduler (exact parity path);
  * tpu-batch — the batched (evals x nodes x picks) scan kernel, E evals
               per launch, including host-side input assembly and result
               translation (the production dispatch path).

Prints ONE JSON line: headline = tpu-batch placements/sec,
vs_baseline = ratio over the oracle.  Details go to stderr.
"""
from __future__ import annotations

import json
import random
import sys
import time

import numpy as np

from nomad_tpu import mock
from nomad_tpu.ops.batch import (
    batch_plan_picks_shared,
    chained_plan_picks_shared,
)
from nomad_tpu.sched.feasible import shuffle_permutation
from nomad_tpu.sched.generic_sched import ServiceScheduler
from nomad_tpu.sched.testing import Harness
from nomad_tpu.sched.util import ready_nodes_in_dcs
from nomad_tpu.structs import (
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    alloc_name,
    compute_node_class,
)

N_NODES = 10_000
N_ALLOCS = 100_000
TG_COUNT = 10  # placements per eval
ORACLE_EVALS = 12
TPU_SEL_EVALS = 8
BATCH_E = 256
BATCH_ROUNDS = 3
CHECK_EVALS = 6
SEED_BASE = 1000


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_cluster():
    rng = random.Random(7)
    h = Harness()
    log(f"building {N_NODES} nodes / {N_ALLOCS} allocs ...")
    nodes = []
    t0 = time.time()
    for i in range(N_NODES):
        n = mock.node()
        n.node_resources.cpu = rng.choice([8000, 16000, 32000])
        n.node_resources.memory_mb = rng.choice([16384, 32768, 65536])
        nodes.append(n)
    # one computed-class hash per spec bucket, not per node
    class_cache = {}
    for n in nodes:
        key = (n.node_resources.cpu, n.node_resources.memory_mb)
        if key not in class_cache:
            class_cache[key] = compute_node_class(n)
        n.computed_class = class_cache[key]
        h.store.upsert_node(n)
    log(f"  nodes in {time.time()-t0:.1f}s")

    t0 = time.time()
    filler_job = mock.job(id="filler")
    allocs = []
    for i in range(N_ALLOCS):
        node = nodes[rng.randrange(N_NODES)]
        allocs.append(
            Allocation(
                namespace="default",
                job_id="filler",
                job=filler_job,
                task_group="web",
                name=alloc_name("filler", "web", i),
                node_id=node.id,
                allocated_resources=AllocatedResources(
                    tasks={
                        "web": AllocatedTaskResources(
                            cpu=rng.choice([100, 200, 500]),
                            memory_mb=rng.choice([128, 256, 512]),
                        )
                    },
                    shared=AllocatedSharedResources(disk_mb=100),
                ),
                client_status="running",
            )
        )
    h.store.upsert_allocs(allocs)
    log(f"  allocs in {time.time()-t0:.1f}s")
    return h, nodes


def make_eval(h, i):
    job = mock.job(id=f"bench-{i}")
    job.task_groups[0].count = TG_COUNT
    h.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    return job, ev


def bench_scheduler(h, evals, use_tpu, label, warmup=False):
    h.reject_plan = True  # score against pristine state every eval
    if warmup:
        # compile the kernels outside the timed region (production
        # amortizes jit compiles across the process lifetime)
        wjob, wev = make_eval(h, 9999)
        h.process(
            ServiceScheduler, wev, use_tpu=use_tpu, seed=SEED_BASE
        )
        h.plans.pop()
    placements = {}
    t0 = time.time()
    for i, (job, ev) in enumerate(evals):
        h.process(
            ServiceScheduler, ev, use_tpu=use_tpu, seed=SEED_BASE + i
        )
        plan = h.plans[-1]
        placements[i] = sorted(
            (a.name, a.node_id)
            for v in plan.node_allocation.values()
            for a in v
        )
    dt = time.time() - t0
    n_placed = sum(len(p) for p in placements.values())
    rate = n_placed / dt
    log(
        f"{label}: {len(evals)} evals, {n_placed} placements in "
        f"{dt:.2f}s -> {rate:.1f} placements/s"
    )
    return rate, placements


def bench_batched(h, check_against=None):
    """Batched kernel path: E evals per launch; node columns ship once,
    per-eval data is just the walk orders + ask scalars."""
    table = h.store.node_table
    C = table.capacity
    snap = h.store.snapshot()
    job0 = mock.job(id="shape-probe")
    job0.task_groups[0].count = TG_COUNT
    node_list, _ = ready_nodes_in_dcs(snap, job0.datacenters)
    n_cand = len(node_list)
    import math

    limit = max(2, math.ceil(math.log2(n_cand)))
    base_rows = np.asarray(
        [table.row_of[n.id] for n in node_list], dtype=np.int32
    )
    present = set(base_rows.tolist())
    rest = np.asarray(
        [r for r in range(C) if r not in present], dtype=np.int32
    )
    feasible = np.zeros(C, dtype=bool)
    feasible[base_rows] = True
    feasible &= table.eligible & table.active

    import jax

    dev_cols = jax.device_put(
        (table.cpu_total, table.mem_total, table.disk_total,
         feasible, table.cpu_used, table.mem_used, table.disk_used)
    )

    def perms_for(eval_indexes):
        out = np.empty((len(eval_indexes), C), dtype=np.int32)
        for k, i in enumerate(eval_indexes):
            rng = random.Random(SEED_BASE + i)
            order = shuffle_permutation(rng, n_cand)
            out[k, :n_cand] = base_rows[order]
            out[k, n_cand:] = rest
        return out

    def dispatch(eval_indexes):
        """Async kernel dispatch; returns the device rows array."""
        E = len(eval_indexes)
        perms = perms_for(eval_indexes)
        return batch_plan_picks_shared(
            *dev_cols,
            perms,
            np.full(E, 500.0),
            np.full(E, 256.0),
            np.full(E, 300.0),
            np.full(E, TG_COUNT, np.int32),
            np.full(E, limit, np.int32),
            np.int32(n_cand),
            TG_COUNT,
        )

    def translate(eval_indexes, rows):
        out = {}
        for k, i in enumerate(eval_indexes):
            out[i] = sorted(
                (alloc_name(f"bench-{i}", "web", p), table.node_ids[r])
                for p, r in enumerate(rows[k])
                if r >= 0
            )
        return out

    def launch(eval_indexes):
        return translate(
            eval_indexes, np.asarray(dispatch(eval_indexes))
        )

    log("tpu-batch: compiling ...")
    t0 = time.time()
    launch(list(range(BATCH_E)))
    log(f"  compile+warmup {time.time()-t0:.1f}s")

    all_placements = {}
    eval_latencies = []
    t0 = time.time()
    # pipeline: dispatch is async, so assemble batch k+1 while the device
    # runs batch k; only the result fetch synchronizes
    batches = [
        list(range(i * BATCH_E, (i + 1) * BATCH_E))
        for i in range(BATCH_ROUNDS)
    ]
    inflight = None  # (eval_indexes, device rows, dispatch time)
    for batch_ids in batches:
        t_dispatch = time.time()
        perms = perms_for(batch_ids)
        E = len(batch_ids)
        rows_dev = batch_plan_picks_shared(
            *dev_cols,
            perms,
            np.full(E, 500.0),
            np.full(E, 256.0),
            np.full(E, 300.0),
            np.full(E, TG_COUNT, np.int32),
            np.full(E, limit, np.int32),
            np.int32(n_cand),
            TG_COUNT,
        )
        if inflight is not None:
            prev_ids, prev_rows, prev_t = inflight
            all_placements.update(translate(prev_ids, np.asarray(prev_rows)))
            eval_latencies.extend(
                [(time.time() - prev_t) * 1000.0] * len(prev_ids)
            )
        inflight = (batch_ids, rows_dev, t_dispatch)
    prev_ids, prev_rows, prev_t = inflight
    all_placements.update(translate(prev_ids, np.asarray(prev_rows)))
    eval_latencies.extend([(time.time() - prev_t) * 1000.0] * len(prev_ids))
    dt = time.time() - t0
    n_placed = sum(len(p) for p in all_placements.values())
    rate = n_placed / dt
    per_eval_ms = dt / (BATCH_ROUNDS * BATCH_E) * 1000
    lat = np.sort(np.asarray(eval_latencies))
    p50 = float(lat[int(0.50 * (len(lat) - 1))])
    p99 = float(lat[int(0.99 * (len(lat) - 1))])
    log(
        f"tpu-batch: {BATCH_ROUNDS * BATCH_E} evals, {n_placed} "
        f"placements in {dt:.2f}s -> {rate:.1f} placements/s "
        f"({per_eval_ms:.2f} ms/eval amortized; eval latency "
        f"p50={p50:.1f}ms p99={p99:.1f}ms)"
    )

    # chained (serially-equivalent) variant: the production pipeline's
    # launch shape; timed for reference
    t0 = time.time()
    for i in range(BATCH_ROUNDS):
        ids = list(range(i * BATCH_E, (i + 1) * BATCH_E))
        E = len(ids)
        np.asarray(chained_plan_picks_shared(
            *dev_cols,
            perms_for(ids),
            np.full(E, 500.0),
            np.full(E, 256.0),
            np.full(E, 300.0),
            np.full(E, TG_COUNT, np.int32),
            np.full(E, limit, np.int32),
            np.int32(n_cand),
            TG_COUNT,
        ))
    dt_chained = time.time() - t0
    log(
        f"tpu-batch-chained (serially-equivalent): "
        f"{n_placed / dt_chained:.1f} placements/s"
    )

    if check_against:
        matched = mismatched = 0
        got = launch(sorted(check_against))
        for i, oracle_p in check_against.items():
            if [nid for _, nid in got[i]] == [
                nid for _, nid in oracle_p
            ]:
                matched += 1
            else:
                mismatched += 1
        log(
            f"tpu-batch decision check vs oracle: {matched} identical, "
            f"{mismatched} divergent"
        )
    return rate, p50, p99


def main():
    h, nodes = build_cluster()

    oracle_evals = [make_eval(h, i) for i in range(ORACLE_EVALS)]
    oracle_rate, oracle_placements = bench_scheduler(
        h, oracle_evals, use_tpu=False, label="oracle"
    )

    tpu_evals = [make_eval(h, i) for i in range(TPU_SEL_EVALS)]
    # warm the kernel once before timing
    h.reject_plan = True
    h.process(
        ServiceScheduler, tpu_evals[0][1], use_tpu=True, seed=SEED_BASE
    )
    tpu_rate, tpu_placements = bench_scheduler(
        h, tpu_evals, use_tpu=True, label="tpu-sel", warmup=True
    )

    # per-select parity on the shared prefix
    same = sum(
        1
        for i in range(min(ORACLE_EVALS, TPU_SEL_EVALS))
        if [n for _, n in oracle_placements[i]]
        == [n for _, n in tpu_placements[i]]
    )
    log(
        f"tpu-sel decision check vs oracle: {same}/"
        f"{min(ORACLE_EVALS, TPU_SEL_EVALS)} evals identical"
    )

    check = {
        i: oracle_placements[i] for i in range(CHECK_EVALS)
    }
    batch_rate, p50, p99 = bench_batched(h, check)

    print(
        json.dumps(
            {
                "metric": "placements_per_sec_10k_nodes_binpack",
                "value": round(batch_rate, 1),
                "unit": "placements/s",
                "vs_baseline": round(batch_rate / oracle_rate, 2),
                "p99_eval_latency_ms": round(p99, 1),
                "p50_eval_latency_ms": round(p50, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
