"""Benchmark: placements/sec on a simulated 10k-node / 100k-alloc cluster
(BASELINE.json config family; binpack service placements).

The HEADLINE number is measured through the REAL pipeline on both sides:
evals enqueued into the eval broker, drained by a scheduling worker,
plans verified and committed by the plan applier, allocs written to
state.  The two sides differ only in the worker:

  * e2e-oracle — the sequential Worker running the host iterator chain
                 (the "stock binpack" baseline);
  * e2e-tpu    — the BatchWorker: simulation pre-pass + one chained
                 (evals x nodes x picks) kernel launch per run +
                 prescored replay (serially equivalent, bit-identical
                 plans).

Both servers process the SAME job stream; the bench checks the
placement streams are identical (the serial-equivalence contract) and
zeroes `vs_baseline` in the output when they diverge, so a correctness
regression can never read as a perf win.
Latency percentiles come from a separate paced-arrival phase at ~80% of
the measured throughput, so they measure service latency rather than
burst queueing delay.

Secondary (kernel-only) numbers for the non-chained and chained kernels
are reported as extra JSON keys; details go to stderr.

Prints ONE JSON line.
"""
from __future__ import annotations

import json
import os
import random
import sys
import time

import numpy as np

from nomad_tpu import mock
from nomad_tpu.structs import (
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    alloc_name,
    compute_node_class,
)

# this sandbox's scheduler can park a timed wait far past its timeout;
# the broker's opt-in notify watchdog bounds the damage
os.environ.setdefault("NOMAD_TPU_BROKER_WATCHDOG", "1")
# block on cold kernel compiles instead of falling back: the bench
# measures steady-state throughput, and for unlimited-walk shapes
# (spread/affinity at 5k nodes) a sequential fallback eval costs ~25s —
# far more than the compile it is dodging
os.environ.setdefault("NOMAD_TPU_SYNC_COMPILE", "1")
# virtual host devices for the multichip sweep: the flag only affects
# the CPU platform, so on real hardware the sweep sees the real chips
# and this is inert.  Must be set before jax initializes its backends.
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

N_NODES = int(os.environ.get("BENCH_NODES", 10_000))
N_ALLOCS = int(os.environ.get("BENCH_ALLOCS", 100_000))
TG_COUNT = 10  # placements per eval
E2E_JOBS = int(os.environ.get("BENCH_E2E_JOBS", 384))
E2E_ORACLE_JOBS = int(os.environ.get("BENCH_E2E_ORACLE_JOBS", 48))
PACED_JOBS = int(os.environ.get("BENCH_PACED_JOBS", 128))
# paced-arrival latency sweep: jobs per offered-load point (3 points)
SWEEP_JOBS = int(os.environ.get("BENCH_SWEEP_JOBS", 64))
# offered load as fractions of the measured eval throughput
SWEEP_FRACTIONS = (0.25, 0.5, 0.75)
BATCH_E = 256
BATCH_ROUNDS = 3
SEED_BASE = 1000
# also run the kernel-only microbench after the e2e bench
WITH_KERNEL = os.environ.get(
    "BENCH_WITH_KERNEL", os.environ.get("BENCH_KERNEL_ONLY", "1")
) == "1"


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def populate(store):
    """Fill a state store with the simulated cluster."""
    rng = random.Random(7)
    nodes = []
    t0 = time.time()
    for i in range(N_NODES):
        # deterministic ids so placement streams are comparable across
        # independently-populated stores (oracle vs tpu server)
        n = mock.node(id=f"bench-node-{i:05d}")
        n.node_resources.cpu = rng.choice([8000, 16000, 32000])
        n.node_resources.memory_mb = rng.choice([16384, 32768, 65536])
        nodes.append(n)
    # one computed-class hash per spec bucket, not per node
    class_cache = {}
    for n in nodes:
        key = (n.node_resources.cpu, n.node_resources.memory_mb)
        if key not in class_cache:
            class_cache[key] = compute_node_class(n)
        n.computed_class = class_cache[key]
        store.upsert_node(n)
    log(f"  nodes in {time.time()-t0:.1f}s")

    t0 = time.time()
    filler_job = mock.job(id="filler")
    store.upsert_job(filler_job)
    allocs = []
    for i in range(N_ALLOCS):
        node = nodes[rng.randrange(N_NODES)]
        allocs.append(
            Allocation(
                namespace="default",
                job_id="filler",
                job=filler_job,
                task_group="web",
                name=alloc_name("filler", "web", i),
                node_id=node.id,
                allocated_resources=AllocatedResources(
                    tasks={
                        "web": AllocatedTaskResources(
                            cpu=rng.choice([100, 200, 500]),
                            memory_mb=rng.choice([128, 256, 512]),
                        )
                    },
                    shared=AllocatedSharedResources(disk_mb=100),
                ),
                client_status="running",
            )
        )
    store.upsert_allocs(allocs)
    log(f"  allocs in {time.time()-t0:.1f}s")
    return nodes


def bench_job(i, prefix="e2e"):
    job = mock.job(id=f"{prefix}-{i}")
    job.task_groups[0].count = TG_COUNT
    return job


def job_placements(store, job_id):
    return sorted(
        (a.name, a.node_id)
        for a in store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    )


# ---------------------------------------------------------------------------
# end-to-end pipeline bench
# ---------------------------------------------------------------------------


def build_server(batch_pipeline):
    from nomad_tpu.server import Server

    # huge heartbeat TTL: the simulated nodes never heartbeat, and a
    # bench run longer than the TTL would otherwise mass-expire them
    # mid-stream (every alloc lost -> eval flood -> zero placements)
    server = Server(
        num_schedulers=1,
        seed=SEED_BASE,
        batch_pipeline=batch_pipeline,
        heartbeat_ttl=1e9,
    )
    log(
        f"building {N_NODES} nodes / {N_ALLOCS} allocs "
        f"({'tpu' if batch_pipeline else 'oracle'} server) ..."
    )
    populate(server.store)
    server.start()
    return server


def run_stream(server, n_jobs, label, prefix, paced_rate=None):
    """Register n_jobs jobs, wait for the pipeline to drain, and return
    (placements_per_sec, latencies_ms, placements_by_job,
    latency_ms_by_eval_id).

    With paced_rate (evals/s), registrations are spaced to measure
    service latency instead of burst queueing delay.  The per-eval-id
    latency map keys are flight-recorder trace ids, so a sweep can
    attach p99 exemplars that resolve on /v1/traces/<id>."""
    acks = {}
    submits = {}
    orig_ack = server.broker.ack

    def timed_ack(eval_id, token):
        orig_ack(eval_id, token)
        acks[eval_id] = time.time()

    server.broker.ack = timed_ack
    try:
        t0 = time.time()
        interval = 1.0 / paced_rate if paced_rate else 0.0
        next_t = time.time()
        evs = []
        for i in range(n_jobs):
            if interval:
                now = time.time()
                if now < next_t:
                    time.sleep(next_t - now)
                next_t += interval
            ev = server.register_job(bench_job(i, prefix))
            submits[ev.id] = time.time()
            evs.append(ev)
        ok = server.drain_to_idle(timeout=max(120.0, n_jobs * 0.5))
        dt = time.time() - t0
    finally:
        server.broker.ack = orig_ack
    if not ok:
        log(f"  WARNING: {label} did not drain to idle")
    placements = {}
    n_placed = 0
    for i in range(n_jobs):
        p = job_placements(server.store, f"{prefix}-{i}")
        placements[i] = p
        n_placed += len(p)
    lat_by_id = {
        e: (acks[e] - submits[e]) * 1000.0
        for e in acks
        if e in submits
    }
    lat = sorted(lat_by_id.values())
    rate = n_placed / dt if dt > 0 else 0.0
    log(
        f"{label}: {n_jobs} evals, {n_placed} placements in {dt:.2f}s "
        f"-> {rate:.1f} placements/s"
    )
    return rate, lat, placements, lat_by_id


def pct(lat, q):
    if not lat:
        return 0.0
    return float(lat[min(len(lat) - 1, int(q * (len(lat) - 1)))])


def trace_stage_seconds():
    """Trace-derived per-stage seconds over the recorder ring: spans
    named ``batch_worker.<stage>`` summed per stage, dividing each
    chunk/run-wide span's duration by its ``members`` attr so the
    totals are comparable with the worker's ``timings`` accounting
    (which observes those stages once per chunk/run, not per eval)."""
    from nomad_tpu.trace import TRACE

    agg = {}
    for trace in TRACE.recent(limit=100_000, full=True):
        names = {s["name"] for s in trace["spans"]}
        # the wave path's "replay" stage time is commit_wait + commit
        # (exactly the interval _commit_wave observes into timings) —
        # but ONLY for evals that committed speculatively.  A
        # conflicted member records commit_wait AND a serial
        # batch_worker.replay span while timings sees only the
        # latter, so counting its wait would double-book the stage.
        committed = "replay.commit" in names
        for span in trace["spans"]:
            name = span["name"]
            if name.startswith("batch_worker."):
                stage = name.split(".", 1)[1]
                if stage in ("gulp", "fallback"):
                    continue  # marks, not timed stages
            elif name == "replay.commit" or (
                name == "replay.commit_wait" and committed
            ):
                stage = "replay"
            else:
                continue
            dur = span["dur_ms"] or 0.0
            members = span["attrs"].get("members", 1) or 1
            agg[stage] = agg.get(stage, 0.0) + dur / 1000.0 / members
    return agg


def cross_check_trace_stages(trace_stages, stage_times):
    """Log the flight-recorder stage breakdown against the worker's
    e2e_stage_times_s; returns the worst relative deviation over the
    stages big enough to judge (>50ms on both sides).  The two views
    measure the same intervals through different plumbing, so a large
    gap means per-eval attribution went wrong — visible here instead
    of silently shipping bogus traces."""
    worst = 0.0
    for stage, t_timings in sorted(stage_times.items()):
        t_trace = trace_stages.get(stage, 0.0)
        if min(t_trace, t_timings) < 0.05:
            continue
        rel = abs(t_trace - t_timings) / t_timings
        worst = max(worst, rel)
        log(
            f"  trace-vs-timings {stage}: trace={t_trace:.2f}s "
            f"timings={t_timings:.2f}s ({rel * 100:.0f}% apart)"
        )
    return worst


def latency_sweep(server, eval_rate):
    """Offered-load vs latency curve (ROADMAP item 1: the <250 ms p99
    target must be tracked per round, not one-off): three paced-
    arrival phases at SWEEP_FRACTIONS of the measured eval
    throughput, each reporting p50/p99 service latency plus the
    flight-recorder trace ids of the evals at-or-past p99 — the
    `latency_sweep` block in BENCH json, with exemplars that resolve
    on /v1/traces/<id> (and in the bundled traces.json) so a slow
    round is debuggable from its artifacts alone."""
    from nomad_tpu.trace import TRACE

    out = []
    for s_i, frac in enumerate(SWEEP_FRACTIONS):
        offered = max(1.0, eval_rate * frac)
        _rate, lat, _p, lat_ids = run_stream(
            server,
            SWEEP_JOBS,
            f"latency-sweep {frac:.2f}x ({offered:.1f} evals/s)",
            f"sweep{s_i}",
            paced_rate=offered,
        )
        p50, p99 = pct(lat, 0.50), pct(lat, 0.99)
        # p99 exemplars: the slowest evals' trace ids (bounded), only
        # ones the flight-recorder ring still holds
        recorded = {
            t["eval_id"] for t in TRACE.recent(limit=100_000)
        }
        exemplars = [
            e
            for e, ms in sorted(
                lat_ids.items(), key=lambda kv: -kv[1]
            )
            if ms >= p99 and e in recorded
        ][:3]
        log(
            f"  sweep {frac:.2f}x: offered={offered:.1f}/s "
            f"p50={p50:.1f}ms p99={p99:.1f}ms "
            f"exemplars={exemplars}"
        )
        out.append(
            {
                "offered_fraction": frac,
                "offered_evals_per_sec": round(offered, 2),
                "n_evals": len(lat),
                "p50_ms": round(p50, 1),
                "p99_ms": round(p99, 1),
                "p99_trace_exemplars": exemplars,
            }
        )
    return out


def bench_e2e():
    # --- oracle side -----------------------------------------------------
    oracle = build_server(batch_pipeline=False)
    try:
        oracle_rate, _lat, oracle_p, _ids = run_stream(
            oracle, E2E_ORACLE_JOBS, "e2e-oracle", "e2e"
        )
    finally:
        oracle.stop()

    # --- tpu side --------------------------------------------------------
    tpu = build_server(batch_pipeline=True)
    try:
        # warmup: compile the chained kernel shapes outside the timed
        # region (production amortizes jit compiles across the process),
        # then stop the warm jobs + drain so the timed stream starts
        # from decision-equivalent state to the oracle server's
        log("e2e-tpu: warmup/compile ...")
        t0 = time.time()
        tpu.workers[0].warm_shapes()
        run_stream(tpu, 2, "  warmup", "warm")
        for i in range(2):
            tpu.deregister_job("default", f"warm-{i}")
        tpu.drain_to_idle(timeout=30)
        worker = tpu.workers[0]
        log(f"  warmup {time.time()-t0:.1f}s")
        for k in worker.timings:
            worker.timings[k] = 0.0
        # drop warmup traces so the trace-derived stage breakdown
        # covers exactly the timed stream
        from nomad_tpu.trace import TRACE as _trace

        _trace.clear()

        tpu_rate, _lat, tpu_p, _ids = run_stream(
            tpu, E2E_JOBS, "e2e-tpu", "e2e"
        )
        stats = dict(worker.timings)
        trace_stages = trace_stage_seconds()
        cross_check_trace_stages(trace_stages, stats)
        total_staged = sum(stats.values()) or 1.0
        # the prescore pipeline reports per-stage: assemble (host
        # input staging), launch (non-blocking dispatch) and fetch
        # (time blocked on device results) — so a regression in any
        # sub-stage is visible across rounds instead of lumped into
        # one opaque "prescore" number
        log(
            "e2e-tpu stage times: "
            + ", ".join(
                f"{k}={v:.2f}s ({v/total_staged*100:.0f}%)"
                for k, v in stats.items()
            )
            + f"; prescored={worker.prescored} fallbacks={worker.fallbacks}"
        )
        prescore_share = (
            stats.get("assemble", 0.0)
            + stats.get("launch", 0.0)
            + stats.get("fetch", 0.0)
        ) / total_staged
        # replay share + optimistic-replay outcome (the stage PR 2
        # parallelized: speculative wave + conflict-checked commit)
        replay_share = stats.get("replay", 0.0) / total_staged
        replay_stats = {
            "speculative": worker.replay_speculative,
            "conflicts": worker.replay_conflicts,
            "serial_fallbacks": worker.replay_serial_fallbacks,
        }
        spec_total = (
            worker.replay_speculative + worker.replay_conflicts
        )
        replay_conflict_rate = (
            worker.replay_conflicts / spec_total if spec_total else 0.0
        )
        log(
            f"e2e-tpu replay: share={replay_share:.3f} "
            f"speculative={replay_stats['speculative']} "
            f"conflicts={replay_stats['conflicts']} "
            f"serial_fallbacks={replay_stats['serial_fallbacks']} "
            f"(conflict rate {replay_conflict_rate:.3f})"
        )

        # parity: the serially-equivalent contract means the common
        # prefix of the two streams must be bit-identical
        n_check = min(E2E_ORACLE_JOBS, E2E_JOBS)
        same = sum(
            1 for i in range(n_check) if oracle_p[i] == tpu_p[i]
        )
        log(
            f"e2e decision check vs oracle: {same}/{n_check} "
            f"evals identical"
        )

        # --- paced phase for service latency ----------------------------
        paced_rate = max(2.0, tpu_rate / TG_COUNT * 0.8)
        lat_rate, lat, _p, _lat_ids = run_stream(
            tpu,
            PACED_JOBS,
            f"e2e-tpu-paced ({paced_rate:.0f} evals/s offered)",
            "paced",
            paced_rate=paced_rate,
        )
        p50, p99 = pct(lat, 0.50), pct(lat, 0.99)
        log(
            f"e2e-tpu paced latency: p50={p50:.1f}ms p99={p99:.1f}ms "
            f"({len(lat)} evals)"
        )

        # --- offered-load latency sweep (3 rates) ------------------------
        eval_rate = tpu_rate / TG_COUNT
        sweep = latency_sweep(tpu, eval_rate)
    finally:
        tpu.stop()
    return (
        oracle_rate, tpu_rate, p50, p99, same, stats,
        prescore_share, replay_share, replay_conflict_rate,
        replay_stats, trace_stages, sweep,
    )


# ---------------------------------------------------------------------------
# kernel-only secondary numbers (the r1/r2 microbenchmark, kept for
# comparability)
# ---------------------------------------------------------------------------


def bench_kernel_only():
    """Time the WARMED `batch_plan_picks` (independent evals, vmapped)
    and `chained_plan_picks` (serially-equivalent eval scan) entry
    points.  Runs on a nodes-only world sized by BENCH_KERNEL_NODES
    (default min(BENCH_NODES, 2000), no resident allocs) so the
    microbench is cheap enough to always run — BENCH_CPU_PARITY_r05
    shipped `kernel_*_placements_per_sec: 0.0` because this phase
    never produced a number."""
    from nomad_tpu.ops.batch import (
        BatchInputs,
        batch_plan_picks,
        chained_plan_picks,
    )
    from nomad_tpu.sched.feasible import shuffle_permutation
    from nomad_tpu.sched.util import ready_nodes_in_dcs
    from nomad_tpu.state.store import StateStore

    n_nodes = int(
        os.environ.get("BENCH_KERNEL_NODES", min(N_NODES, 2000))
    )
    kernel_e = int(os.environ.get("BENCH_KERNEL_E", 64))
    store = StateStore()
    log(f"kernel-only: building {n_nodes}-node world ...")
    rng = random.Random(7)
    nodes = []
    class_cache = {}
    for i in range(n_nodes):
        n = mock.node(id=f"kern-node-{i:05d}")
        n.node_resources.cpu = rng.choice([8000, 16000, 32000])
        n.node_resources.memory_mb = rng.choice([16384, 32768])
        key = (n.node_resources.cpu, n.node_resources.memory_mb)
        if key not in class_cache:
            class_cache[key] = compute_node_class(n)
        n.computed_class = class_cache[key]
        nodes.append(n)
        store.upsert_node(n)
    table = store.node_table
    C = table.capacity
    snap = store.snapshot()
    job0 = mock.job(id="shape-probe")
    node_list, _ = ready_nodes_in_dcs(snap, job0.datacenters)
    n_cand = len(node_list)
    import math

    limit = max(2, math.ceil(math.log2(n_cand)))
    base_rows = np.asarray(
        [table.row_of[n.id] for n in node_list], dtype=np.int32
    )
    present = set(base_rows.tolist())
    rest = np.asarray(
        [r for r in range(C) if r not in present], dtype=np.int32
    )
    feasible = np.zeros(C, dtype=bool)
    feasible[base_rows] = True
    feasible &= table.eligible & table.active

    def perms_for(eval_indexes):
        out = np.empty((len(eval_indexes), C), dtype=np.int32)
        for k, i in enumerate(eval_indexes):
            order = shuffle_permutation(
                random.Random(SEED_BASE + i), n_cand
            )
            out[k, :n_cand] = base_rows[order]
            out[k, n_cand:] = rest
        return out

    import jax

    # everything launch-invariant ships to the device ONCE, outside
    # the timed loop — only the per-eval walk orders vary per round —
    # so the reported rate times the warmed kernel, not host staging
    # and H2D transfer production launches never pay (they read the
    # BatchWorker's persistent device mirror)
    E = kernel_e
    node_cols = jax.device_put(
        (table.cpu_total, table.mem_total, table.disk_total)
    )
    shared = {
        f: jax.device_put(v)
        for f, v in dict(
            feasible=np.broadcast_to(feasible, (E, C)),
            base_cpu_used=np.broadcast_to(table.cpu_used, (E, C)),
            base_mem_used=np.broadcast_to(table.mem_used, (E, C)),
            base_disk_used=np.broadcast_to(
                table.disk_used, (E, C)
            ),
            base_collisions=np.zeros((E, C), np.int32),
            penalty=np.zeros((E, C), dtype=bool),
            affinity_score=np.zeros((E, C)),
            ask_cpu=np.full(E, 500.0),
            ask_mem=np.full(E, 256.0),
            ask_disk=np.full(E, 300.0),
            desired_count=np.full(E, TG_COUNT, np.int32),
            limit=np.full(E, limit, np.int32),
            distinct_hosts=np.zeros(E, dtype=bool),
        ).items()
    }

    def launch(fn, ids):
        return np.asarray(
            fn(
                *node_cols,
                BatchInputs(perm=perms_for(ids), **shared),
                np.int32(n_cand),
                TG_COUNT,
            )
        )

    results = {}
    for name, fn in (
        ("kernel-batch", batch_plan_picks),
        ("kernel-chained", chained_plan_picks),
    ):
        launch(fn, list(range(kernel_e)))  # compile+warm
        t0 = time.time()
        n_placed = 0
        for r in range(BATCH_ROUNDS):
            ids = list(
                range(r * kernel_e, (r + 1) * kernel_e)
            )
            rows = launch(fn, ids)
            n_placed += int((rows >= 0).sum())
        dt = time.time() - t0
        rate = n_placed / dt if dt > 0 else 0.0
        results[name] = rate
        log(f"{name}: {n_placed} placements in {dt:.2f}s -> {rate:.1f}/s")
    return results


# ---------------------------------------------------------------------------
# BASELINE configs 2-5 (each through the real pipeline on both sides)
# ---------------------------------------------------------------------------


def _mk_server(batch_pipeline, seed=SEED_BASE, tpu_select=False):
    from nomad_tpu.server import Server

    server = Server(
        num_schedulers=1,
        seed=seed,
        batch_pipeline=batch_pipeline,
        heartbeat_ttl=1e9,
    )
    if tpu_select:
        cfg = server.store.get_scheduler_config()
        cfg.tpu_scheduler_enabled = True
        server.store.set_scheduler_config(cfg)
    return server


def _run_jobs(server, jobs, drain=300.0):
    """Register jobs, wait for drain; returns (wall, placements map)."""
    t0 = time.time()
    for job in jobs:
        server.register_job(job)
    ok = server.drain_to_idle(timeout=drain)
    dt = time.time() - t0
    if not ok:
        log("  WARNING: did not drain")
    out = {}
    n = 0
    for job in jobs:
        p = job_placements(server.store, job.id)
        out[job.id] = p
        n += len(p)
    return dt, out, n


def _compare(label, build_nodes, build_jobs, n_oracle_jobs=None,
             tpu_select=False, prefill=None):
    """Generic config runner: same node set + job stream through an
    oracle server and a batch-pipeline server; returns the result dict."""
    results = {}
    placements_by_side = {}
    prime_by_side = {}
    pipeline_stats = {}
    for side, batchy in (("oracle", False), ("tpu", True)):
        server = _mk_server(batchy, tpu_select=tpu_select and batchy)
        try:
            for node in build_nodes():
                server.store.upsert_node(node)
            if prefill is not None:
                prefill(server.store)
            server.start()
            if batchy:
                server.workers[0].warm_shapes()
            jobs = build_jobs()
            if side == "oracle" and n_oracle_jobs:
                jobs = jobs[:n_oracle_jobs]
            # untimed priming: one clone of the stream's first job
            # compiles whatever trace variants this config's shapes
            # need (spread/port/device columns that warm_shapes
            # doesn't cover) OUTSIDE the timed window, on BOTH sides
            # so the pre-stream cluster state stays identical
            # (system jobs excepted: a cloned system job would claim
            # every feasible node and block the real one — and system
            # evals run the per-select path whose compile the e2e
            # phase already warmed)
            if jobs and jobs[0].type != "system":
                import copy as _copy

                # prime batches compile this config's trace variants
                # (spread/port/device columns) through the pipelined
                # chunk launches at EVERY adaptive chunk width (the
                # batch side pins the width per prime batch — gulp
                # timing would otherwise make bucket coverage racy),
                # so nothing compiles inside the timed window; the
                # clones' placements join the parity contract and
                # their capacity is returned before timing
                # (desired-stop allocs are terminal for usage)
                primes = []
                bw = server.workers[0] if batchy else None
                orig_cw = bw._chunk_width if bw is not None else None
                try:
                    for b, count, width in (
                        ("a", 1, 2), ("c", 3, 4), ("b", 12, 8)
                    ):
                        if bw is not None:
                            bw._chunk_width = (
                                lambda n, _w=width: min(
                                    _w, bw.batch_max
                                )
                            )
                        batch = []
                        for k in range(count):
                            p = _copy.deepcopy(jobs[0])
                            p.id = f"prime-{b}{k}-{jobs[0].id}"
                            batch.append(p)
                        _, pmap, _n = _run_jobs(
                            server, batch, drain=600.0
                        )
                        primes.extend(batch)
                        for p in batch:
                            prime_by_side.setdefault(side, {})[
                                p.id
                            ] = pmap.get(p.id)
                finally:
                    if bw is not None:
                        bw._chunk_width = orig_cw
                for p in primes:
                    server.deregister_job(
                        "default", p.id, purge=True
                    )
                if not server.drain_to_idle(timeout=120.0):
                    log(
                        f"{label} {side}: WARNING prime purge did "
                        "not drain; timed stream may include stop "
                        "work"
                    )
            dt, pmap, n = _run_jobs(server, jobs)
            rate = n / dt if dt else 0.0
            results[side] = rate
            placements_by_side[side] = pmap
            if batchy:
                w = server.workers[0]
                covered = w.prescored + w.fallbacks
                pipeline_stats = {
                    "prescored": w.prescored,
                    "fallbacks": w.fallbacks,
                    "cold_shape_fallbacks": w.cold_shape_fallbacks,
                    "mesh_used": w.mesh_used,
                    "fallback_rate": round(
                        w.fallbacks / covered, 3
                    ) if covered else 0.0,
                }
            log(f"{label} {side}: {n} placements in {dt:.2f}s -> {rate:.1f}/s")
        finally:
            server.stop()
    o_p, t_p = placements_by_side["oracle"], placements_by_side["tpu"]
    common = [k for k in o_p if k in t_p]
    same = sum(1 for k in common if o_p[k] == t_p[k])
    parity_ok = same == len(common)
    if prime_by_side and prime_by_side.get(
        "oracle"
    ) != prime_by_side.get("tpu"):
        parity_ok = False
        log(f"{label} PRIME divergence: {prime_by_side}")
    log(f"{label} parity: {same}/{len(common)}")
    return {
        "placements_per_sec": round(results["tpu"], 1),
        "oracle_placements_per_sec": round(results["oracle"], 1),
        "vs_baseline": round(results["tpu"] / results["oracle"], 2)
        if results["oracle"] and parity_ok
        else 0.0,
        "parity": f"{same}/{len(common)}",
        **pipeline_stats,
    }


def config2_batch():
    """Batch scheduler: 1k queued allocs over 1k nodes (BASELINE #2)."""
    n_nodes = int(os.environ.get("BENCH_C2_NODES", 1000))
    n_jobs = int(os.environ.get("BENCH_C2_JOBS", 100))

    def nodes():
        rng = random.Random(11)
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"c2-node-{i:05d}")
            n.node_resources.cpu = rng.choice([8000, 16000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            out.append(n)
        _share_classes(out)
        return out

    def jobs():
        out = []
        for i in range(n_jobs):
            job = mock.job(id=f"c2-{i}")
            job.type = "batch"
            job.task_groups[0].count = 10
            job.task_groups[0].tasks[0].resources.cpu = 300
            out.append(job)
        return out

    return _compare("config2-batch-1k/1k", nodes, jobs)


def config3_spread_affinity():
    """Spread + node-affinity across 3 DCs, 5k nodes (BASELINE #3).
    The oracle walks EVERY candidate per pick here (spread/affinity
    disable the log2 visit limit, stack.go:164) — the regime the
    vectorized kernel is built for."""
    from nomad_tpu.structs import Affinity, Spread, SpreadTarget

    n_nodes = int(os.environ.get("BENCH_C3_NODES", 5000))
    n_jobs = int(os.environ.get("BENCH_C3_JOBS", 48))
    n_oracle = int(os.environ.get("BENCH_C3_ORACLE_JOBS", 4))

    def nodes():
        rng = random.Random(13)
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"c3-node-{i:05d}")
            n.datacenter = rng.choice(["dc1", "dc2", "dc3"])
            n.node_resources.cpu = rng.choice([8000, 16000, 32000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            out.append(n)
        _share_classes(out)
        return out

    def jobs():
        out = []
        for i in range(n_jobs):
            job = mock.job(id=f"c3-{i}")
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = 6
            tg.tasks[0].resources.cpu = 300
            job.spreads = [
                Spread(
                    attribute="${node.datacenter}",
                    weight=60,
                    targets=[
                        SpreadTarget(value="dc1", percent=50),
                        SpreadTarget(value="dc2", percent=30),
                    ],
                )
            ]
            job.affinities = [
                Affinity(
                    ltarget="${node.datacenter}",
                    operand="=",
                    rtarget="dc2",
                    weight=35,
                )
            ]
            out.append(job)
        return out

    return _compare(
        "config3-spread-affinity-5k", nodes, jobs,
        n_oracle_jobs=n_oracle,
    )


def config4_system_devices_preemption():
    """System job + GPU device constraint + preemption, 10k nodes
    (BASELINE #4).  System evals run through the sequential worker on
    both sides; the tpu side selects with TPUSystemStack (vectorized
    fleet scoring) via the runtime scheduler-config toggle."""
    from nomad_tpu.structs import PreemptionConfig

    n_nodes = int(os.environ.get("BENCH_C4_NODES", 10000))
    gpu_every = 10  # 10% of the fleet has GPUs

    def nodes():
        rng = random.Random(17)
        out = []
        for i in range(n_nodes):
            if i % gpu_every == 0:
                n = mock.nvidia_node(id=f"c4-node-{i:05d}")
            else:
                n = mock.node(id=f"c4-node-{i:05d}")
            n.node_resources.cpu = rng.choice([8000, 16000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            out.append(n)
        _share_classes(out)
        return out

    def prefill(store):
        # low-priority filler on the GPU nodes so preemption has work
        filler = mock.job(id="c4-filler")
        filler.priority = 10
        store.upsert_job(filler)
        allocs = []
        rng = random.Random(19)
        for i in range(n_nodes // gpu_every):
            node_id = f"c4-node-{i * gpu_every:05d}"
            allocs.append(
                Allocation(
                    namespace="default",
                    job_id="c4-filler",
                    job=filler,
                    task_group="web",
                    name=alloc_name("c4-filler", "web", i),
                    node_id=node_id,
                    allocated_resources=AllocatedResources(
                        tasks={
                            "web": AllocatedTaskResources(
                                cpu=rng.choice([6000, 7000]),
                                memory_mb=8192,
                            )
                        },
                        shared=AllocatedSharedResources(disk_mb=100),
                    ),
                    client_status="running",
                )
            )
        store.upsert_allocs(allocs)
        cfg = store.get_scheduler_config()
        cfg.preemption_config = PreemptionConfig(
            system_scheduler_enabled=True
        )
        store.set_scheduler_config(cfg)

    def jobs():
        from nomad_tpu.structs import RequestedDevice

        job = mock.system_job(id="c4-system")
        job.priority = 80
        tg = job.task_groups[0]
        tg.tasks[0].resources.cpu = 4000
        tg.tasks[0].resources.memory_mb = 4096
        # device ask restricts the fleet to the GPU nodes and
        # exercises the DeviceChecker mask + device assignment
        tg.tasks[0].resources.devices = [
            RequestedDevice(name="nvidia/gpu", count=1)
        ]
        return [job]

    return _compare(
        "config4-system-gpu-preempt-10k", nodes, jobs,
        tpu_select=True, prefill=prefill,
    )


def config5_c2m_replay():
    """C2M-style mixed service+batch replay at 10k nodes (BASELINE #5).
    Container scale is set by BENCH_C5_ALLOCS (default 200k resident
    allocs — a 10x-scaled-down C2M so the bench fits host memory; the
    stream shape matches: mixed types, steady churn)."""
    n_nodes = int(os.environ.get("BENCH_C5_NODES", 10000))
    n_allocs = int(os.environ.get("BENCH_C5_ALLOCS", 200_000))
    n_jobs = int(os.environ.get("BENCH_C5_JOBS", 192))
    n_oracle = int(os.environ.get("BENCH_C5_ORACLE_JOBS", 24))

    def nodes():
        rng = random.Random(23)
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"c5-node-{i:05d}")
            n.node_resources.cpu = rng.choice([16000, 32000])
            n.node_resources.memory_mb = rng.choice([32768, 65536])
            out.append(n)
        _share_classes(out)
        return out

    def prefill(store):
        filler = mock.job(id="c5-filler")
        store.upsert_job(filler)
        rng = random.Random(29)
        allocs = []
        for i in range(n_allocs):
            allocs.append(
                Allocation(
                    namespace="default",
                    job_id="c5-filler",
                    job=filler,
                    task_group="web",
                    name=alloc_name("c5-filler", "web", i),
                    node_id=f"c5-node-{rng.randrange(n_nodes):05d}",
                    allocated_resources=AllocatedResources(
                        tasks={
                            "web": AllocatedTaskResources(
                                cpu=rng.choice([100, 200]),
                                memory_mb=rng.choice([128, 256]),
                            )
                        },
                        shared=AllocatedSharedResources(disk_mb=50),
                    ),
                    client_status="running",
                )
            )
        store.upsert_allocs(allocs)

    def jobs():
        rng = random.Random(31)
        out = []
        for i in range(n_jobs):
            job = mock.job(id=f"c5-{i}")
            if i % 3 == 2:
                job.type = "batch"
            job.task_groups[0].count = rng.choice([5, 10, 20])
            job.task_groups[0].tasks[0].resources.cpu = rng.choice(
                [200, 400]
            )
            out.append(job)
        return out

    return _compare(
        "config5-c2m-replay", nodes, jobs, n_oracle_jobs=n_oracle,
    )


def _share_classes(nodes):
    cache = {}
    for n in nodes:
        key = (
            n.node_resources.cpu,
            n.node_resources.memory_mb,
            n.datacenter,
            bool(n.node_resources.devices),
        )
        if key not in cache:
            cache[key] = compute_node_class(n)
        n.computed_class = cache[key]


WITH_CONFIGS = os.environ.get("BENCH_CONFIGS", "1") == "1"
WITH_MULTICHIP = os.environ.get("BENCH_MULTICHIP", "1") == "1"
WITH_CLUSTER_FAILOVER = (
    os.environ.get("BENCH_CLUSTER_FAILOVER", "1") == "1"
)
WITH_TRACE_OVERHEAD = os.environ.get("BENCH_TRACE_OVERHEAD", "1") == "1"
WITH_EXPLAIN_OVERHEAD = (
    os.environ.get("BENCH_EXPLAIN_OVERHEAD", "1") == "1"
)
WITH_DEVICE = os.environ.get("BENCH_DEVICE", "1") == "1"
WITH_STORM = os.environ.get("BENCH_STORM", "1") == "1"
WITH_POLICY = os.environ.get("BENCH_POLICY", "1") == "1"
WITH_SWARM = os.environ.get("BENCH_SWARM", "1") == "1"
WITH_CLUSTER_FANOUT = (
    os.environ.get("BENCH_CLUSTER_FANOUT", "1") == "1"
)
WITH_BIGWORLD = os.environ.get("BENCH_BIGWORLD", "1") == "1"
WITH_CLUSTER_OBS = os.environ.get("BENCH_CLUSTER_OBS", "1") == "1"
WITH_SLO = os.environ.get("BENCH_SLO", "1") == "1"
WITH_FEDERATION = os.environ.get("BENCH_FEDERATION", "1") == "1"


def bench_bigworld():
    """Million-node composed topology as a bench block
    (nomad_tpu.loadgen.bigworld_smoke): a >=1M-node / >=10M-alloc
    synthetic world seeded through the raft log, planned by >=2
    fan-out followers each heading a live 2-process jax.distributed
    mesh (pod streaming, NOMAD_TPU_POD_CHECK digest parity on every
    launch) — exporting placements/s, each follower's per-host
    bytes-per-flush gauge, and the snapshot catch-up time of a
    SIGKILLed-and-restarted follower (`bigworld` in BENCH json).
    The reduced-scale twin of this block (with the single-server
    placement-parity oracle) gates tools/ci_check.sh.
    BENCH_BIGWORLD=0 opts out; BENCH_BIGWORLD_{NODES,ALLOCS,JOBS,
    STORM_JOBS,TIMEOUT,ORACLE} rescale."""
    from nomad_tpu.loadgen.bigworld_smoke import run_bigworld

    t0 = time.time()
    block = run_bigworld(
        nodes=int(os.environ.get("BENCH_BIGWORLD_NODES", 1_000_000)),
        allocs=int(
            os.environ.get("BENCH_BIGWORLD_ALLOCS", 10_000_000)
        ),
        jobs=int(os.environ.get("BENCH_BIGWORLD_JOBS", 8)),
        storm_jobs=int(
            os.environ.get("BENCH_BIGWORLD_STORM_JOBS", 8)
        ),
        # the full-scale world seeds for minutes per replica; the
        # oracle replay doubles the drive, so it is opt-in here and
        # always-on in the reduced-scale ci_check gate
        oracle=os.environ.get("BENCH_BIGWORLD_ORACLE", "0") == "1",
        timeout=float(
            os.environ.get("BENCH_BIGWORLD_TIMEOUT", 3600)
        ),
    )
    flushes = ", ".join(
        f"{addr}={int(b)}B"
        for addr, b in block["bytes_per_flush_per_host"].items()
    )
    log(
        f"bigworld: {block['world']['nodes']} nodes / "
        f"{block['world']['allocs']} allocs, "
        f"{block['topology']['followers']} followers x "
        f"{block['topology']['procs_per_follower']}-proc mesh: "
        f"{block['placements_per_s']}/s, flush {flushes}, "
        f"catchup {block['catchup']['catchup_s']}s, "
        f"lost={block['lost']} ({time.time() - t0:.1f}s)"
    )
    return block


def bench_cluster_fanout():
    """Follower scheduling fan-out as a bench block
    (nomad_tpu.server.fanout_bench): the same storm-shaped workload
    played through 1/3/5-server clusters with NOMAD_TPU_FANOUT=1,
    recording per-topology wall placements/s AND planning-capacity
    placements/s (evals / bottleneck server's worker-thread CPU —
    the scheduling-throughput bound once each server owns real
    cores; the whole bench shares one process, so on a single-core
    harness wall clock cannot scale), the 3v1/5v1 capacity
    speedups, zero-lost and placement-set-parity verdicts
    (`cluster_fanout` in BENCH json).  The acceptance bar is >=2x
    capacity from 1 to 3 servers with parity intact.
    BENCH_CLUSTER_FANOUT=0 opts out; BENCH_FANOUT_{FAMILIES,JOBS,
    NODES,REPS} rescale."""
    from nomad_tpu.server.fanout_bench import run_fanout_bench

    t0 = time.time()
    block = run_fanout_bench(
        server_counts=(1, 3, 5),
        families=int(os.environ.get("BENCH_FANOUT_FAMILIES", 600)),
        jobs_per=int(os.environ.get("BENCH_FANOUT_JOBS", 1)),
        nodes=int(os.environ.get("BENCH_FANOUT_NODES", 2048)),
        reps=int(os.environ.get("BENCH_FANOUT_REPS", 5)),
    )
    ratios = ", ".join(
        f"{r['servers']}s={r['capacity_placements_per_s']}/s"
        f"(wall {r['wall_placements_per_s']}/s)"
        for r in block["runs"]
    )
    log(
        f"cluster fanout: ok={block['ok']} capacity {ratios} "
        f"(3v1 {block['speedup_3v1']}x, 5v1 {block['speedup_5v1']}x) "
        f"lost={block['lost_total']} parity={block['parity_ok']} "
        f"({time.time() - t0:.1f}s)"
    )
    return block


def bench_cluster_obs():
    """Cluster-scope observability costs (`cluster_obs` in BENCH
    json): (a) stitched-trace overhead on the fan-out path — the same
    3-server fan-out workload with the recorder on vs off,
    interleaved A/B with a discarded warmup and min-of-reps (the
    trace-overhead protocol), the `on` runs also proving stitching
    engaged (>=1 trace with spans from >=2 servers, zero orphans);
    (b) leader fan-in query latency (`cluster_query("metrics")`)
    at 1 vs 3 vs 5 servers, median of 15 queries; (c) the metric
    history ring's memory footprint at full depth on a
    representative registry.  The acceptance contract is <5% trace
    overhead (same tolerance shape as tests/test_trace.py) with
    stitching engaged.  BENCH_CLUSTER_OBS=0 opts out;
    BENCH_OBS_{FAMILIES,NODES,REPS} rescale."""
    from nomad_tpu.server.cluster import TestCluster
    from nomad_tpu.server.fanout_bench import _run_topology
    from nomad_tpu.telemetry import Metrics, MetricsHistory
    from nomad_tpu.trace import TRACE

    t0 = time.time()
    families = int(os.environ.get("BENCH_OBS_FAMILIES", 120))
    nodes = int(os.environ.get("BENCH_OBS_NODES", 256))
    reps = int(os.environ.get("BENCH_OBS_REPS", 2))

    knobs = {
        "NOMAD_TPU_FANOUT": "1",
        "NOMAD_TPU_BATCH_MAX": "8",
        "NOMAD_TPU_FANOUT_LEASE_N": "4",
    }
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)

    def run_once(enabled, tag):
        TRACE.set_enabled(enabled)
        TRACE.clear()
        r = _run_topology(
            3,
            nodes=nodes,
            families=families,
            jobs_per=1,
            tag=f"ob{tag}",
        )
        stitched = 0
        orphans = 0
        if enabled:
            for trace in TRACE.recent(limit=256, full=True):
                if not trace["complete"]:
                    continue
                orphans += trace["orphans"]
                lanes = {
                    (s.get("attrs") or {}).get("server_id")
                    for s in trace["spans"]
                }
                if len(lanes) >= 2:
                    stitched += 1
        log(
            f"cluster-obs {tag} trace="
            f"{'on' if enabled else 'off'}: "
            f"{r['placements_total']} placements in "
            f"{r['wall_s']:.2f}s"
            + (
                f" stitched={stitched} orphans={orphans}"
                if enabled
                else ""
            )
        )
        return r["wall_s"], stitched, orphans

    times = {True: [], False: []}
    stitched_min = None
    orphans_total = 0
    was_enabled = TRACE.enabled
    try:
        # discarded warmup: first run of this topology pays the XLA
        # compiles for its launch shapes
        run_once(True, "warmup")
        for rep in range(reps):
            for enabled in (True, False):
                dt, stitched, orphans = run_once(
                    enabled, f"r{rep}"
                )
                times[enabled].append(dt)
                if enabled:
                    stitched_min = (
                        stitched
                        if stitched_min is None
                        else min(stitched_min, stitched)
                    )
                    orphans_total += orphans
    finally:
        TRACE.set_enabled(was_enabled)
        TRACE.clear()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    t_on, t_off = min(times[True]), min(times[False])
    pct = (t_on - t_off) / t_off * 100.0 if t_off else 0.0
    # the <5% contract with the same additive slack the unit gate
    # uses: tiny absolute wall times make pure ratios noise-bound
    overhead_ok = t_on <= t_off * 1.05 + 0.2

    # -- fan-in query latency vs topology size -------------------
    fanin = {}
    for n in (1, 3, 5):
        cluster = TestCluster(
            n, heartbeat_ttl=300.0, name_prefix=f"obq{n}-"
        )
        try:
            cluster.start()
            leader = cluster.wait_for_leader(timeout=30.0)
            leader.metrics.incr("obs.bench_probe")
            samples = []
            for _ in range(15):
                q0 = time.perf_counter()
                out = leader.cluster_query("metrics")
                samples.append(
                    (time.perf_counter() - q0) * 1000.0
                )
                assert out["asked"] == n and not out["unreachable"]
            samples.sort()
            fanin[f"{n}_servers_ms"] = round(
                samples[len(samples) // 2], 3
            )
        finally:
            cluster.stop()

    # -- history-ring footprint at full depth --------------------
    m = Metrics()
    for i in range(48):
        m.incr(f"obs.bench_counter_{i:02d}")
    for i in range(12):
        m.set_gauge(f"obs.bench_gauge_{i:02d}", float(i))
    for i in range(8):
        for v in range(512):
            m.add_sample(f"obs.bench_sample_{i}_ms", float(v))
    hist = MetricsHistory(m, windows=60, interval_s=60.0)
    for _ in range(60):
        hist.snapshot_once()
    ring_bytes = len(json.dumps(hist.to_dict()))

    block = {
        "ok": bool(
            overhead_ok
            and (stitched_min or 0) > 0
            and orphans_total == 0
        ),
        "families": families,
        "nodes": nodes,
        "reps": reps,
        "trace_on_s": round(t_on, 3),
        "trace_off_s": round(t_off, 3),
        "stitched_overhead_pct": round(pct, 2),
        "overhead_ok": overhead_ok,
        "stitched_traces_min": stitched_min,
        "orphan_spans": orphans_total,
        "fanin_query_latency": fanin,
        "history_ring": {
            "windows": 60,
            "total_bytes": ring_bytes,
            "bytes_per_window": round(ring_bytes / 60.0, 1),
        },
    }
    log(
        f"cluster obs: ok={block['ok']} overhead "
        f"on={t_on:.2f}s off={t_off:.2f}s ({pct:+.1f}%) "
        f"stitched>={stitched_min} orphans={orphans_total} "
        f"fanin={fanin} ring={ring_bytes}B "
        f"({time.time() - t0:.1f}s)"
    )
    return block


def bench_slo():
    """Control-loop flight-data costs (`slo` in BENCH json): (a) the
    decision ledger's overhead — the same config2-like batch stream
    as the trace-overhead bench with the ledger on vs
    ``NOMAD_TPU_DECISIONS=0``, interleaved A/B with a discarded
    warmup and min-of-reps; the acceptance contract is <3% (the
    ledger is one dict build + a lock'd append per CHANGED choice, so
    it should be noise); (b) a site-coverage soak — a scaled swarm
    run (overload sheds + mass node-death storms against the real
    HTTP API) plus a 3-server fan-out round — proving the
    decision-ledger lint is non-vacuous at runtime: the chunk-width,
    admission, overload, storm and fan-out sites all wrote records;
    (c) the SLO engine's burn-rate grades over a real history ring
    after a placement round.  BENCH_SLO=0 opts out;
    BENCH_SLO_{NODES,JOBS,REPS} and BENCH_SLO_SWARM_* rescale."""
    from nomad_tpu.decisions import DECISIONS
    from nomad_tpu.loadgen.swarm_smoke import run_swarm
    from nomad_tpu.server.fanout_bench import _run_topology

    t0 = time.time()
    n_nodes = int(os.environ.get("BENCH_SLO_NODES", 300))
    n_jobs = int(os.environ.get("BENCH_SLO_JOBS", 48))
    reps = int(os.environ.get("BENCH_SLO_REPS", 2))

    def nodes():
        rng = random.Random(13)
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"sl-node-{i:05d}")
            n.node_resources.cpu = rng.choice([8000, 16000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            out.append(n)
        _share_classes(out)
        return out

    slo_report = {}

    def run_once(enabled, tag, capture_slo=False):
        DECISIONS.set_enabled(enabled)
        DECISIONS.clear()
        server = _mk_server(True)
        try:
            for node in nodes():
                server.store.upsert_node(node)
            server.start()
            server.workers[0].warm_shapes()
            jobs = []
            for i in range(n_jobs):
                job = mock.job(id=f"sl-{tag}-{i}")
                job.type = "batch"
                job.task_groups[0].count = 10
                job.task_groups[0].tasks[0].resources.cpu = 300
                jobs.append(job)
            dt, _pmap, n = _run_jobs(server, jobs)
            if capture_slo:
                # grade the round through the real ring: >=2
                # snapshots so counter deltas exist
                server.metrics_history.snapshot_once()
                server.metrics_history.snapshot_once()
                st = server.slo.status()
                slo_report.update(
                    worst=st["worst"],
                    objectives={
                        o["name"]: o["status"]
                        for o in st["objectives"]
                    },
                )
            log(
                f"slo-overhead {tag} "
                f"ledger={'on' if enabled else 'off'}:"
                f" {n} placements in {dt:.2f}s"
            )
            return dt
        finally:
            server.stop()

    times = {True: [], False: []}
    counts = {}
    try:
        # discarded warmup (pays the XLA compiles for this node
        # count); also the slo-status capture round
        run_once(True, "warmup", capture_slo=True)
        for rep in range(reps):
            for enabled in (True, False):
                times[enabled].append(run_once(enabled, f"r{rep}"))

        # -- site-coverage soak ----------------------------------
        # the decision-ledger lint proves every registered site HAS
        # a record call; this proves the calls actually fire under
        # the workloads they steer
        DECISIONS.set_enabled(True)
        DECISIONS.clear()
        swarm = run_swarm(
            nodes=int(os.environ.get("BENCH_SLO_SWARM_NODES", 600)),
            submitters=int(
                os.environ.get("BENCH_SLO_SWARM_SUBMITTERS", 240)
            ),
            death=int(os.environ.get("BENCH_SLO_SWARM_DEATH", 120)),
            ttl_s=float(os.environ.get("BENCH_SLO_SWARM_TTL", 8.0)),
            base_jobs=int(
                os.environ.get("BENCH_SLO_SWARM_BASE_JOBS", 150)
            ),
        )
        # targeted admission probe: a non-batchable (sticky-disk)
        # arrival mid-chain is the deterministic way to fire the
        # admission-defer gate (the swarm's arrivals usually coalesce
        # into storms instead)
        probe = _mk_server(True)
        probe_worker = probe.workers[0]
        fired = []
        orig_launch = probe_worker._launch_chunk

        def hooked(asm, c0, c1, carry, check_ready):
            if not fired:
                fired.append(True)
                sticky = mock.job(id="slo-adm-sticky")
                sticky.task_groups[0].ephemeral_disk.sticky = True
                probe.register_job(sticky)
            return orig_launch(asm, c0, c1, carry, check_ready)

        probe_worker._launch_chunk = hooked
        try:
            pn = []
            for i in range(12):
                n = mock.node(id=f"sl-adm-node-{i:02d}")
                pn.append(n)
            _share_classes(pn)
            for n in pn:
                probe.register_node(n)
            for i in range(4):
                job = mock.job(id=f"sl-adm-{i}")
                job.type = "batch"
                job.task_groups[0].count = 8
                probe.register_job(job)
            probe.start()
            probe.drain_to_idle(60)
        finally:
            probe.stop()

        fanout_knobs = {
            "NOMAD_TPU_FANOUT": "1",
            "NOMAD_TPU_BATCH_MAX": "8",
            "NOMAD_TPU_FANOUT_LEASE_N": "4",
        }
        saved = {k: os.environ.get(k) for k in fanout_knobs}
        os.environ.update(fanout_knobs)
        try:
            _run_topology(
                3,
                nodes=int(
                    os.environ.get("BENCH_SLO_FANOUT_NODES", 128)
                ),
                families=int(
                    os.environ.get("BENCH_SLO_FANOUT_FAMILIES", 48)
                ),
                jobs_per=1,
                tag="slf",
            )
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        counts = DECISIONS.counts()
    finally:
        DECISIONS.set_enabled(True)
        DECISIONS.clear()

    t_on, t_off = min(times[True]), min(times[False])
    pct = (t_on - t_off) / t_off * 100.0 if t_off else 0.0
    # <3% with the same additive slack shape the other overhead
    # gates use: tiny absolute wall times make pure ratios noisy
    overhead_ok = t_on <= t_off * 1.03 + 0.2
    required = (
        "chunk_width",
        "admission_defer",
        "overload_mode",
        "storm_trigger",
        "fanout_lease",
    )
    missing = sorted(s for s in required if not counts.get(s))
    block = {
        "ok": bool(
            overhead_ok and not missing and swarm.get("ok")
        ),
        "nodes": n_nodes,
        "jobs": n_jobs,
        "reps": reps,
        "ledger_on_s": round(t_on, 3),
        "ledger_off_s": round(t_off, 3),
        "ledger_overhead_pct": round(pct, 2),
        "overhead_ok": overhead_ok,
        "site_records": counts,
        "sites_missing": missing,
        "swarm_ok": swarm.get("ok"),
        "swarm_violations": swarm.get("violations", []),
        "slo_status": slo_report,
    }
    log(
        f"slo: ok={block['ok']} ledger overhead on={t_on:.2f}s "
        f"off={t_off:.2f}s ({pct:+.1f}%) sites={sorted(counts)} "
        f"missing={missing} worst={slo_report.get('worst')} "
        f"({time.time() - t0:.1f}s)"
    )
    return block


def bench_swarm():
    """Swarm-scale SLO harness as a bench block
    (nomad_tpu.loadgen.swarm_smoke): a >=2k-node heartbeat storm plus
    >=1k concurrent HTTP submitters with an injected 500-node mass
    death — exporting heartbeat success, shed/accepted/deferred
    counts, the death wave's storm-solve count and the
    flight-recorder p99 exemplars (`swarm` in BENCH json).
    BENCH_SWARM=0 opts out; BENCH_SWARM_{NODES,SUBMITTERS,DEATH}
    rescale."""
    from nomad_tpu.loadgen.swarm_smoke import run_swarm

    t0 = time.time()
    block = run_swarm(
        nodes=int(os.environ.get("BENCH_SWARM_NODES", 2200)),
        submitters=int(
            os.environ.get("BENCH_SWARM_SUBMITTERS", 1100)
        ),
        death=int(os.environ.get("BENCH_SWARM_DEATH", 500)),
    )
    log(
        f"swarm: ok={block['ok']} "
        f"hb={block['heartbeat_success']:.4%} "
        f"sheds={block['sheds']:.0f} "
        f"death {block['death_nodes']} nodes in "
        f"{block['storm_solves']:.0f} solve(s), "
        f"eval p99 {block['eval_latency_p99_ms']}ms "
        f"({time.time() - t0:.1f}s)"
    )
    return block


def bench_federation():
    """Geo-plane SLO harness as a bench block
    (nomad_tpu.loadgen.geo_smoke): two 3-server regions federated
    over one WAN — cross-region forward latency, fan-out registration
    latency, shed-redirect p99, region-kill detect/failover times and
    the wan-reads-stay-zero verdict (`federation` in BENCH json).
    BENCH_FEDERATION=0 opts out; BENCH_FEDERATION_FLOOD rescales the
    shed flood."""
    from nomad_tpu.loadgen.geo_smoke import run_geo

    t0 = time.time()
    block = run_geo(
        flood_submitters=int(
            os.environ.get("BENCH_FEDERATION_FLOOD", 96)
        ),
    )
    log(
        f"federation: ok={block['ok']} "
        f"forward p99 {block['forward_p99_ms']}ms "
        f"fanout max {block['fanout_register_max_ms']}ms "
        f"kill detect {block['kill_detect_s']}s "
        f"failover p99 {block['failover_p99_s']}s "
        f"({time.time() - t0:.1f}s)"
    )
    return block


def bench_storm():
    """Mass drain + scale-up replay: hundreds of pending evals of ONE
    job family backlogged in the broker (the whole family registers
    before leadership, so restore_evals enqueues it as one wave —
    exactly the shape a drain or dispatch storm leaves), A/B'd
    storm-on (`NOMAD_TPU_STORM=1`: one global assignment solve per
    drained family prefix) vs storm-off (the per-eval chunk chain).
    Exports placements/s per mode, the speedup, solver
    rounds-to-converge / fallback / divergence counters, and the
    aggregate placement-quality delta (sum of normalized scores) so
    the relaxed serial equivalence is quantified, not just
    permitted."""
    n_nodes = int(os.environ.get("BENCH_STORM_NODES", 2000))
    n_evals = int(os.environ.get("BENCH_STORM_EVALS", 480))
    reps = int(os.environ.get("BENCH_STORM_REPS", 2))

    def nodes():
        rng = random.Random(21)
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"st-node-{i:05d}")
            n.node_resources.cpu = rng.choice([8000, 16000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            out.append(n)
        _share_classes(out)
        return out

    def run_once(storm_on, tag):
        knobs = {
            "NOMAD_TPU_STORM": "1" if storm_on else "0",
            "NOMAD_TPU_STORM_MIN": os.environ.get(
                "BENCH_STORM_MIN", "8"
            ),
            # one solve must cover the whole replayed backlog, or
            # the A/B measures solve-count-dependent compile churn
            "NOMAD_TPU_STORM_MAX": os.environ.get(
                "BENCH_STORM_MAX", "512"
            ),
        }
        saved = {k: os.environ.get(k) for k in knobs}
        os.environ.update(knobs)
        server = None
        try:
            server = _mk_server(True)
            for node in nodes():
                server.store.upsert_node(node)
            jobs = []
            for i in range(n_evals):
                job = mock.job(
                    id=f"stormfam-{tag}/dispatch-{i:04d}"
                )
                job.type = "batch"
                job.task_groups[0].count = 1
                # asks sized so binpack scores are non-trivial
                # (~25% utilization per placement): the
                # placement-quality delta below would be vacuous on
                # near-zero BestFit-v3 scores
                job.task_groups[0].tasks[0].resources.cpu = 2000
                job.task_groups[0].tasks[
                    0
                ].resources.memory_mb = 4096
                jobs.append(job)
                server.register_job(job)
            t0 = time.time()
            server.start()
            drained = server.drain_to_idle(timeout=300.0)
            dt = time.time() - t0
            placed = 0
            score_sum = 0.0
            for job in jobs:
                for a in server.store.allocs_by_job(
                    "default", job.id
                ):
                    if a.terminal_status():
                        continue
                    placed += 1
                    if a.metrics is not None:
                        # winner's normalized score, falling back to
                        # its binpack component (the prescored exact
                        # verify records binpack for every winner;
                        # normalized-score only for walked nodes)
                        for sm in a.metrics.score_meta:
                            if sm.node_id == a.node_id:
                                score_sum += sm.scores.get(
                                    "normalized-score",
                                    sm.scores.get(
                                        "binpack", sm.norm_score
                                    ),
                                )
                                break
            terminal = sum(
                1
                for job in jobs
                for e in server.store.evals_by_job(
                    "default", job.id
                )
                if e.terminal_status()
            )
            worker = server.workers[0]
            stats = {
                "solves": worker.storm_solves,
                "evals": worker.storm_evals,
                "fallbacks": worker.storm_fallbacks,
                "divergent_rows": worker.storm_divergent,
                "rounds": server.metrics.get_gauge("storm.rounds"),
            }
            lost = n_evals - terminal + len(server.broker.failed())
            log(
                f"storm {tag} mode={'on' if storm_on else 'off'}: "
                f"{placed} placements in {dt:.2f}s "
                f"({placed / dt:.0f}/s), lost={lost}, "
                f"score_sum={score_sum:.2f}, {stats}"
            )
            return dt, placed, score_sum, lost, stats
        finally:
            if server is not None:
                server.stop()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # discarded warmups: each mode's first run pays its own XLA
    # compiles (solver shapes on, chain shapes off) for this arena
    run_once(True, "warm1")
    run_once(False, "warm0")
    best = {}
    for rep in range(reps):
        for on in (True, False):
            dt, placed, score_sum, lost, stats = run_once(
                on, f"r{rep}"
            )
            key = "on" if on else "off"
            if key not in best or dt < best[key][0]:
                best[key] = (dt, placed, score_sum, lost, stats)
    dt_on, placed_on, score_on, lost_on, stats_on = best["on"]
    dt_off, placed_off, score_off, lost_off, _stats_off = best["off"]
    rate_on = placed_on / dt_on if dt_on else 0.0
    rate_off = placed_off / dt_off if dt_off else 0.0
    return {
        "evals": n_evals,
        "nodes": n_nodes,
        "storm_placements_per_s": round(rate_on, 1),
        "baseline_placements_per_s": round(rate_off, 1),
        "storm_speedup": round(rate_on / rate_off, 2)
        if rate_off
        else 0.0,
        "solver_rounds_to_converge": stats_on["rounds"],
        "storm_solves": stats_on["solves"],
        "storm_fallbacks": stats_on["fallbacks"],
        "storm_divergent_rows": stats_on["divergent_rows"],
        # aggregate placement quality: sum of normalized scores over
        # all placed allocs, global solve minus greedy chain — the
        # quantified face of the relaxed serial equivalence
        "placement_quality_delta": round(score_on - score_off, 4),
        "zero_lost": lost_on == 0 and lost_off == 0,
    }


def bench_policy():
    """Policy-weighted scoring A/B (sched/policy.py fused into the
    score kernel).  Three sub-measurements:

    1. **kernel overhead** — the jitted single-select kernel with
       identity weights (throughput 1.0 on every node: present, fused,
       ranking-neutral) vs policy-off, same arena; acceptance is <3%
       added kernel time for the fused terms.
    2. **heterogeneity-aware throughput** — a mixed-node-class world
       (1/3 "fast", 2/3 "slow"), jobs carrying a Gavel-style
       throughput-by-class table, A/B'd NOMAD_TPU_POLICY=1 vs =0:
       placements/s both modes plus the share of placements landing
       on fast nodes (policy-off ~ the fast fraction; policy-on
       should go to ~1.0 while capacity lasts).
    3. **migration cost on a mass replan** — every job destructively
       updated at once (the drain/replan shape), A/B'd on/off: the
       count of replacement allocs that left their incumbent node.
       Stickiness must cut migrations at equal-or-better aggregate
       normalized score."""
    import jax

    from nomad_tpu.ops.score import (
        PolicyTerms,
        ScoreInputs,
        score_and_select_packed,
    )
    from nomad_tpu.structs import PolicySpec

    C = int(os.environ.get("BENCH_POLICY_C", 4096))
    k_reps = int(os.environ.get("BENCH_POLICY_KERNEL_REPS", 300))
    n_nodes = int(os.environ.get("BENCH_POLICY_NODES", 300))
    n_jobs = int(os.environ.get("BENCH_POLICY_JOBS", 64))

    # -- 1. kernel-time overhead with identity weights ---------------
    def _mk_inputs(dtype):
        rng = np.random.default_rng(11)
        base = ScoreInputs(
            cpu_total=np.full(C, 4000.0, dtype),
            mem_total=np.full(C, 8192.0, dtype),
            disk_total=np.full(C, 98304.0, dtype),
            cpu_used=rng.uniform(0, 2000, C).astype(dtype),
            mem_used=rng.uniform(0, 4096, C).astype(dtype),
            disk_used=np.zeros(C, dtype),
            feasible=np.ones(C, dtype=bool),
            collisions=np.zeros(C, dtype=np.int32),
            penalty=np.zeros(C, dtype=bool),
            affinity_score=np.zeros(C, dtype),
            spread_boost=np.zeros(C, dtype),
            perm=np.arange(C, dtype=np.int32),
            ask_cpu=np.asarray(500.0, dtype),
            ask_mem=np.asarray(1024.0, dtype),
            ask_disk=np.asarray(300.0, dtype),
            desired_count=np.asarray(1, np.int32),
            limit=np.asarray(2**31 - 1, np.int32),
            n_candidates=np.asarray(C, np.int32),
        )
        identity = base._replace(
            # identity weights, the hot single-select shape: a
            # pre-scaled all-ones throughput term, no migration group
            # (None group = absent pytree leaf, exactly what tpu_stack
            # stages when the TG has no live allocs)
            policy=PolicyTerms(
                tput_term=np.ones(C, dtype),
                has_tput=np.asarray(1.0, dtype),
                mig_term=None,
            )
        )
        return base, identity

    def measure(dtype):
        base, identity = _mk_inputs(dtype)

        def time_block(inp):
            t0 = time.perf_counter()
            for _ in range(k_reps):
                out = score_and_select_packed(inp)
            jax.block_until_ready(out)
            return time.perf_counter() - t0

        # interleaved min-of-rounds: alternating off/on blocks and
        # taking each side's floor cancels machine drift between the
        # two measurements (sequential blocks read CPU frequency/noise
        # drift as kernel overhead)
        score_and_select_packed(base).block_until_ready()  # compile
        score_and_select_packed(identity).block_until_ready()
        t_off = t_on = None
        for _ in range(8):
            d_off = time_block(base)
            d_on = time_block(identity)
            t_off = d_off if t_off is None else min(t_off, d_off)
            t_on = d_on if t_on is None else min(t_on, d_on)
        pct = round(100.0 * (t_on - t_off) / t_off, 2)
        log(
            f"policy kernel {np.dtype(dtype).name}: "
            f"off={t_off * 1e3 / k_reps:.3f}ms "
            f"on={t_on * 1e3 / k_reps:.3f}ms per select ({pct:+.2f}%)"
        )
        return pct

    # the acceptance metric runs at f32 — the accelerator dtype the
    # production select path compiles at (the f64 build exists for the
    # CPU bit-parity harness and is reported alongside for reference)
    kernel_overhead_pct = measure(np.float32)
    kernel_overhead_pct_f64 = measure(np.float64)

    # -- shared e2e scaffolding --------------------------------------
    def mk_nodes(tag):
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"pol-{tag}-node-{i:04d}")
            n.node_class = "fast" if i % 3 == 0 else "slow"
            n.node_resources.cpu = 8000
            n.node_resources.memory_mb = 16384
            out.append(n)
        _share_classes(out)
        return out

    def run_world(policy_on, tag, migration):
        saved = os.environ.get("NOMAD_TPU_POLICY")
        os.environ["NOMAD_TPU_POLICY"] = "1" if policy_on else "0"
        server = None
        try:
            server = _mk_server(True)
            nodes = mk_nodes(tag)
            for node in nodes:
                server.store.upsert_node(node)
            class_of = {n.id: n.node_class for n in nodes}

            def mk_job(i, env_v):
                job = mock.job(id=f"pol-{tag}-job-{i:03d}")
                job.type = "service"
                job.task_groups[0].count = 1
                job.task_groups[0].tasks[0].resources.cpu = 1500
                job.task_groups[0].tasks[
                    0
                ].resources.memory_mb = 3072
                job.task_groups[0].tasks[0].env = {"V": env_v}
                job.policy = PolicySpec(
                    throughput=(
                        {} if migration
                        else {"fast": 2.0, "slow": 1.0}
                    ),
                    migration_coefficient=(
                        0.5 if migration else 0.0
                    ),
                )
                return job

            jobs = [mk_job(i, "1") for i in range(n_jobs)]
            t0 = time.time()
            for job in jobs:
                server.register_job(job)
            server.start()
            server.drain_to_idle(timeout=300.0)
            dt1 = time.time() - t0
            if migration:
                # filler load that binpack-TIES the incumbent at
                # replan time: each filler alloc parks one node at
                # exactly the incumbent's discounted utilization, so
                # a policy-off replacement sees dozens of
                # equal-scoring hosts and scatters on the tie-break
                # shuffle; the migration penalty breaks the same tie
                # toward the incumbent at an identical winning
                # binpack score (equal aggregate, fewer moves)
                filler = mock.job(id=f"pol-{tag}-filler")
                filler.type = "service"
                filler.task_groups[0].count = n_jobs
                # (6000cpu, 12288mb, 1200disk) == a packed incumbent
                # (5 x 1500/3072/300) minus the replanned alloc's own
                # discount — every fit dimension ties exactly
                filler.task_groups[0].tasks[0].resources.cpu = 6000
                filler.task_groups[0].tasks[
                    0
                ].resources.memory_mb = 12288
                filler.task_groups[0].ephemeral_disk.size_mb = 1200
                server.register_job(filler)
                server.drain_to_idle(timeout=300.0)
                # scale-up wave: as many fresh nodes again join
                # before the replan.  The serial walk's power-of-two-
                # choices window is a seeded shuffle over the
                # candidate list, so the grown list shifts the window
                # off the incumbents — the policy-off replan can no
                # longer see them and churns, while the weighted path
                # (unlimited walk + reschedule penalty) holds every
                # alloc in place at an equal-or-better binpack score
                extra = []
                for i in range(n_nodes):
                    node = mock.node(id=f"pol-{tag}-new-{i:04d}")
                    node.node_class = "slow"
                    node.node_resources.cpu = 8000
                    node.node_resources.memory_mb = 16384
                    extra.append(node)
                _share_classes(extra)
                for node in extra:
                    server.store.upsert_node(node)

            def live_nodes():
                # desired_status filter: a destructive update leaves
                # the predecessor non-terminal but desired=stop
                out = {}
                for job in jobs:
                    for a in server.store.allocs_by_job(
                        "default", job.id
                    ):
                        if (
                            a.desired_status == "run"
                            and not a.terminal_status()
                        ):
                            out[job.id] = a.node_id
                return out

            def score_sum():
                total = 0.0
                for job in jobs:
                    for a in server.store.allocs_by_job(
                        "default", job.id
                    ):
                        if (
                            a.desired_status != "run"
                            or a.terminal_status()
                            or a.metrics is None
                        ):
                            continue
                        # the binpack component is the packing-
                        # quality objective present under BOTH knob
                        # settings (normalized-score folds the policy
                        # terms in, so it isn't mode-comparable)
                        for sm in a.metrics.score_meta:
                            if sm.node_id == a.node_id:
                                total += sm.scores.get(
                                    "binpack", sm.norm_score
                                )
                                break
                return total

            before = live_nodes()
            placed = len(before)
            fast_share = (
                sum(
                    1 for nid in before.values()
                    if class_of.get(nid) == "fast"
                ) / placed
                if placed
                else 0.0
            )
            migrations = None
            dt2 = 0.0
            if migration:
                # mass replan: every job destructively updated in one
                # wave (env change -> replacement placements)
                t0 = time.time()
                for i in range(n_jobs):
                    server.register_job(mk_job(i, "2"))
                server.drain_to_idle(timeout=300.0)
                dt2 = time.time() - t0
                after = live_nodes()
                migrations = sum(
                    1
                    for jid, nid in after.items()
                    if before.get(jid) not in (None, nid)
                )
            rate = placed / dt1 if dt1 else 0.0
            result = {
                "placed": placed,
                "placements_per_s": round(rate, 1),
                "fast_share": round(fast_share, 3),
                "migrations": migrations,
                "replan_s": round(dt2, 2),
                "score_sum": round(score_sum(), 4),
            }
            log(
                f"policy {tag} mode="
                f"{'on' if policy_on else 'off'}: {result}"
            )
            return result
        finally:
            if server is not None:
                server.stop()
            if saved is None:
                os.environ.pop("NOMAD_TPU_POLICY", None)
            else:
                os.environ["NOMAD_TPU_POLICY"] = saved

    # -- 2. heterogeneity-aware throughput A/B -----------------------
    tput_on = run_world(True, "tput-on", migration=False)
    tput_off = run_world(False, "tput-off", migration=False)
    # -- 3. migration-cost-aware mass replan A/B ---------------------
    mig_on = run_world(True, "mig-on", migration=True)
    mig_off = run_world(False, "mig-off", migration=True)

    return {
        "kernel_overhead_pct": kernel_overhead_pct,
        "kernel_overhead_pct_f64": kernel_overhead_pct_f64,
        "kernel_overhead_ok": kernel_overhead_pct < 3.0,
        "throughput": {
            "on": tput_on,
            "off": tput_off,
            # fast-node capture: policy-on must beat the off-mode
            # (~fast-fraction) share
            "fast_share_gain": round(
                tput_on["fast_share"] - tput_off["fast_share"], 3
            ),
        },
        "migration": {
            "on": mig_on,
            "off": mig_off,
            "migrations_avoided": (
                (mig_off["migrations"] or 0)
                - (mig_on["migrations"] or 0)
            ),
            # the acceptance pair: fewer migrations at equal-or-
            # better aggregate normalized score
            "fewer_migrations": (
                (mig_on["migrations"] or 0)
                <= (mig_off["migrations"] or 0)
            ),
            "score_delta": round(
                mig_on["score_sum"] - mig_off["score_sum"], 4
            ),
        },
    }


def bench_multichip():
    """Sweep the sharded chained pipeline over device counts
    (1/2/4/8 on the virtual CPU mesh, the real chip counts on
    hardware): placements/s, host->device bytes per warm mirror
    flush (delta vs full), and per-device HLO FLOPs — the proof
    block for the multi-chip hot path (`multichip` in BENCH json and
    the MULTICHIP_r*.json tail).  The `multihost` row spawns the
    2-process distributed smoke: the same pipeline across PROCESSES
    (per-host flush bytes, sharded-vs-single storm solve)."""
    from nomad_tpu.parallel.multichip import multichip_sweep

    t0 = time.time()
    block = multichip_sweep()
    for p in block["points"]:
        if "skipped" in p:
            log(f"multichip d={p['n_devices']}: skipped")
            continue
        log(
            f"multichip d={p['n_devices']}: "
            f"{p['placements_per_sec']} placements/s, "
            f"{p['per_device_flops']:.3g} flops/device, "
            f"{p['bytes_per_flush_delta']}B delta vs "
            f"{p['bytes_per_flush_full']}B full per flush"
        )
    mh = block.get("multihost", {})
    if "skipped" in mh:
        log(f"multichip multihost: skipped ({mh['skipped']})")
    elif mh:
        log(
            f"multichip multihost: {mh['procs']} procs x "
            f"{mh['devices_per_host']} devices, "
            f"{mh['placements_per_sec']} placements/s e2e, "
            f"{mh['bytes_per_flush_delta_per_host']}B delta vs "
            f"{mh['bytes_per_flush_full_per_host']}B full per host"
            f"/flush, storm sharded "
            f"{mh['storm_solve_sharded_ms']}ms vs single "
            f"{mh['storm_solve_single_device_ms']}ms "
            f"(bit_identical={mh['storm_bit_identical']})"
        )
    log(f"multichip sweep took {time.time() - t0:.1f}s")
    return block


def bench_cluster_failover():
    """Leadership-loss chaos harness as a bench block: a 3-server
    raft cluster survives 5 leader kills + a healed partition under
    continuous eval load (nomad_tpu.raft.chaos_smoke), recording
    every kill's revoke→re-establish detect-to-resume time plus the
    zero-lost / zero-duplicate / monotone-apply verdicts
    (`cluster_failover` in BENCH json).  BENCH_CLUSTER_FAILOVER=0
    opts out."""
    from nomad_tpu.raft.chaos_smoke import run_smoke

    t0 = time.time()
    block = run_smoke(jobs=400, kills=5, nodes=6)
    log(
        f"cluster failover: ok={block['ok']} "
        f"kills={block['kills']} "
        f"detect-to-resume p50 {block['detect_to_resume_p50_s']}s "
        f"max {block['detect_to_resume_max_s']}s, "
        f"{block['placements_total']} placements, "
        f"{block['lost_evals']} lost, "
        f"{block['duplicate_placements']} duplicates "
        f"({time.time() - t0:.1f}s)"
    )
    return block


def bench_device_supervisor():
    """Forced-failover microbench (device supervisor): a small batch
    server with ``NOMAD_TPU_FAULT=wedge_launch`` armed, measuring the
    wall time from the first submit to LOST detection and from
    detection to the first placement committed on the CPU fallback,
    plus the supervisor's probe-latency/failover stats.  Runs after
    the headline benches so the injected fault can't touch them."""
    import copy as _copy

    from nomad_tpu.server import Server

    knobs = {
        "NOMAD_TPU_FAULT": "wedge_launch",
        "NOMAD_TPU_WATCHDOG_MIN_S": "1.0",
        "NOMAD_TPU_WATCHDOG_MAX_S": "1.0",
        "NOMAD_TPU_PROBE_INTERVAL_S": "0.5",
        "NOMAD_TPU_PROBE_TIMEOUT_S": "0.5",
        # the backend is already initialized by this point in the
        # bench; the injected wedge must trip at the 1s budget, not
        # wait out the cold-start grace
        "NOMAD_TPU_INIT_GRACE_S": "1.0",
    }
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    server = None
    try:
        server = Server(
            num_schedulers=1,
            seed=SEED_BASE,
            batch_pipeline=True,
            heartbeat_ttl=1e9,
        )
        rng = random.Random(11)
        cache = {}
        for i in range(200):
            n = mock.node(id=f"devbench-node-{i:04d}")
            n.node_resources.cpu = rng.choice([8000, 16000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            key = (n.node_resources.cpu, n.node_resources.memory_mb)
            if key not in cache:
                cache[key] = compute_node_class(n)
            n.computed_class = cache[key]
            server.store.upsert_node(n)
        server.start()
        sup = server.device_supervisor
        acks = []
        orig_ack = server.broker.ack

        def timed_ack(eval_id, token):
            orig_ack(eval_id, token)
            acks.append(time.monotonic())

        server.broker.ack = timed_ack
        t0 = time.monotonic()
        n_jobs = 32
        for i in range(n_jobs):
            server.register_job(bench_job(i, prefix="devbench"))
        drained = server.drain_to_idle(timeout=60.0)
        server.broker.ack = orig_ack
        t_lost = None
        for h in sup.status()["history"]:
            if h["to"] == "LOST":
                # history stamps wall time; rebase onto the monotonic
                # measurements
                t_lost = time.monotonic() - (time.time() - h["at"])
                break
        detect_s = (t_lost - t0) if t_lost is not None else None
        resume_s = None
        if t_lost is not None:
            after = [a for a in acks if a >= t_lost]
            if after:
                resume_s = after[0] - t_lost
        placed = sum(
            len(job_placements(server.store, f"devbench-{i}"))
            for i in range(n_jobs)
        )
        status = sup.status()
        out = {
            "drained": drained,
            "placements": placed,
            "failover_count": status["failover_count"],
            "watchdog_trips": status["watchdog_trips"],
            "time_degraded_s": status["time_degraded_s"],
            "probe_latency_ms_p50": status["probe_latency_ms"]["p50"],
            "probe_latency_ms_p99": status["probe_latency_ms"]["p99"],
            "detect_s": round(detect_s, 3)
            if detect_s is not None
            else None,
            "detect_to_cpu_resume_s": round(resume_s, 3)
            if resume_s is not None
            else None,
            "state": status["state"],
        }
        log(f"device-supervisor microbench: {json.dumps(out)}")
        return out
    finally:
        if server is not None:
            server.stop()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bench_trace_overhead():
    """Cost of the always-on eval flight recorder: the same
    config2-like batch stream (1k-ish queued allocs) through the batch
    pipeline with tracing on vs NOMAD_TPU_TRACE=0, interleaved A/B/A/B
    with min-of-reps per mode so scheduler noise doesn't masquerade as
    recorder overhead.  Emits ``trace_overhead_pct`` so BENCH_* files
    track the recorder's budget (<5% is the contract in
    tests/test_trace.py)."""
    from nomad_tpu.trace import TRACE

    n_nodes = int(os.environ.get("BENCH_TRACE_NODES", 300))
    n_jobs = int(os.environ.get("BENCH_TRACE_JOBS", 48))
    reps = int(os.environ.get("BENCH_TRACE_REPS", 2))

    def nodes():
        rng = random.Random(11)
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"tr-node-{i:05d}")
            n.node_resources.cpu = rng.choice([8000, 16000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            out.append(n)
        _share_classes(out)
        return out

    def run_once(enabled, tag):
        TRACE.set_enabled(enabled)
        server = _mk_server(True)
        try:
            for node in nodes():
                server.store.upsert_node(node)
            server.start()
            server.workers[0].warm_shapes()
            jobs = []
            for i in range(n_jobs):
                job = mock.job(id=f"tr-{tag}-{i}")
                job.type = "batch"
                job.task_groups[0].count = 10
                job.task_groups[0].tasks[0].resources.cpu = 300
                jobs.append(job)
            dt, _pmap, n = _run_jobs(server, jobs)
            log(
                f"trace-overhead {tag} "
                f"trace={'on' if enabled else 'off'}:"
                f" {n} placements in {dt:.2f}s"
            )
            return dt
        finally:
            server.stop()

    times = {True: [], False: []}
    was_enabled = TRACE.enabled
    try:
        # discarded warmup: the first run of this node-count pays the
        # XLA compiles for its launch shapes, which would otherwise
        # read as recorder overhead in whichever mode ran first
        run_once(True, "warmup")
        for rep in range(reps):
            for enabled in (True, False):
                times[enabled].append(
                    run_once(enabled, f"r{rep}")
                )
    finally:
        TRACE.set_enabled(was_enabled)
        TRACE.clear()
    t_on, t_off = min(times[True]), min(times[False])
    pct_overhead = (t_on - t_off) / t_off * 100.0 if t_off else 0.0
    log(
        f"trace-overhead: on={t_on:.2f}s off={t_off:.2f}s "
        f"-> {pct_overhead:+.1f}%"
    )
    return round(pct_overhead, 2)


def bench_explain_overhead():
    """Cost of the placement-explainability layer: the same
    config2-like batch stream through the batch pipeline with the
    explain layer on vs NOMAD_TPU_EXPLAIN=0, interleaved A/B/A/B with
    min-of-reps per mode (the trace-overhead protocol).  Emits
    ``explain_overhead_pct``; the acceptance contract is <3%
    (tests/test_placement_explain.py gates the capture's per-select
    cost, this gates the pipeline's recording cost)."""
    from nomad_tpu.explain import EXPLAIN

    n_nodes = int(os.environ.get("BENCH_EXPLAIN_NODES", 300))
    n_jobs = int(os.environ.get("BENCH_EXPLAIN_JOBS", 48))
    reps = int(os.environ.get("BENCH_EXPLAIN_REPS", 2))

    def nodes():
        rng = random.Random(12)
        out = []
        for i in range(n_nodes):
            n = mock.node(id=f"ex-node-{i:05d}")
            n.node_resources.cpu = rng.choice([8000, 16000])
            n.node_resources.memory_mb = rng.choice([16384, 32768])
            out.append(n)
        _share_classes(out)
        return out

    def run_once(enabled, tag):
        EXPLAIN.set_enabled(enabled)
        server = _mk_server(True)
        try:
            for node in nodes():
                server.store.upsert_node(node)
            server.start()
            server.workers[0].warm_shapes()
            jobs = []
            for i in range(n_jobs):
                job = mock.job(id=f"ex-{tag}-{i}")
                job.type = "batch"
                job.task_groups[0].count = 10
                job.task_groups[0].tasks[0].resources.cpu = 300
                jobs.append(job)
            dt, _pmap, n = _run_jobs(server, jobs)
            log(
                f"explain-overhead {tag} "
                f"explain={'on' if enabled else 'off'}:"
                f" {n} placements in {dt:.2f}s"
            )
            return dt
        finally:
            server.stop()

    times = {True: [], False: []}
    was_enabled = EXPLAIN.enabled
    try:
        # discarded warmup: first run pays this node-count's XLA
        # compiles, which would read as explain overhead otherwise
        run_once(True, "warmup")
        for rep in range(reps):
            for enabled in (True, False):
                times[enabled].append(
                    run_once(enabled, f"r{rep}")
                )
    finally:
        EXPLAIN.set_enabled(was_enabled)
        EXPLAIN.clear()
    t_on, t_off = min(times[True]), min(times[False])
    pct_overhead = (t_on - t_off) / t_off * 100.0 if t_off else 0.0
    log(
        f"explain-overhead: on={t_on:.2f}s off={t_off:.2f}s "
        f"-> {pct_overhead:+.1f}%"
    )
    return round(pct_overhead, 2)


def bench_configs():
    out = {}
    for name, fn in (
        ("config2_batch_1k", config2_batch),
        ("config3_spread_affinity_5k", config3_spread_affinity),
        ("config4_system_gpu_preempt_10k", config4_system_devices_preemption),
        ("config5_c2m_replay", config5_c2m_replay),
    ):
        try:
            out[name] = fn()
        except Exception as exc:  # noqa: BLE001
            log(f"{name} FAILED: {exc!r}")
            out[name] = {"error": repr(exc)}
    return out


def _preflight() -> None:
    """Bounded accelerator check before building the 10k-node world,
    delegated to the device supervisor's canary machinery
    (``nomad_tpu.device.preflight``): take the cross-process device
    lock, then retry a bounded-time backend init + canary kernel until
    the accelerator answers or the budget passes — failing fast with a
    clear message beats hanging until the driver's timeout."""
    total_s = float(os.environ.get("BENCH_PREFLIGHT_S", 600))
    if total_s <= 0:
        return  # explicit opt-out
    from nomad_tpu.device.preflight import (
        HEALTHY_STATES,
        run_preflight,
    )

    result = run_preflight(total_s=total_s, log=log)
    log(f"preflight: {json.dumps(result)}")
    if result["state"] in HEALTHY_STATES:
        return
    if result["state"] == "LOCK_BUSY":
        log("preflight: accelerator lock busy past deadline; aborting")
        sys.exit(2)
    if result["state"] == "FATAL":
        log(f"preflight: fatal: {result.get('error')}")
        sys.exit(2)
    log(
        f"preflight: accelerator unreachable for {total_s:.0f}s "
        f"({result.get('error')}) — likely a stale tunnel session; "
        "aborting instead of hanging"
    )
    # round-long retry evidence (unattended loops over
    # `python -m nomad_tpu.device.preflight`): surface the attempt
    # log so a failed bench records HOW MUCH recovery was attempted,
    # not just this invocation's preflight
    try:
        import glob as _glob

        here = os.path.dirname(os.path.abspath(__file__))
        candidates = sorted(
            _glob.glob(os.path.join(here, "bench_attempts_*.log"))
        )
        if candidates:
            with open(candidates[-1]) as fh:
                lines = fh.read().splitlines()
            log(
                f"preflight: retry-loop attempt log "
                f"({os.path.basename(candidates[-1])}, "
                f"{len(lines)} lines, last 6): "
                + " | ".join(lines[-6:])
            )
    except OSError:
        pass
    sys.exit(2)


def main():
    from nomad_tpu.device_lock import align_jax_platforms

    # honor an explicit CPU-only env even under a tunnel sitecustomize
    # that pinned jax_platforms via config (config beats env)
    align_jax_platforms()
    _preflight()
    (
        oracle_rate, tpu_rate, p50, p99, same, stage_times,
        prescore_share, replay_share, replay_conflict_rate,
        replay_stats, trace_stages, sweep,
    ) = bench_e2e()
    trace_overhead = (
        bench_trace_overhead() if WITH_TRACE_OVERHEAD else None
    )
    explain_overhead = (
        bench_explain_overhead() if WITH_EXPLAIN_OVERHEAD else None
    )
    configs = bench_configs() if WITH_CONFIGS else {}
    kernel = bench_kernel_only() if WITH_KERNEL else {}
    multichip = {}
    if WITH_MULTICHIP:
        try:
            multichip = bench_multichip()
        except Exception as exc:  # noqa: BLE001
            log(f"multichip sweep FAILED: {exc!r}")
            multichip = {"error": repr(exc)}
    storm = {}
    if WITH_STORM:
        try:
            storm = bench_storm()
        except Exception as exc:  # noqa: BLE001
            log(f"storm scenario FAILED: {exc!r}")
            storm = {"error": repr(exc)}
    policy = {}
    if WITH_POLICY:
        try:
            policy = bench_policy()
        except Exception as exc:  # noqa: BLE001
            log(f"policy scenario FAILED: {exc!r}")
            policy = {"error": repr(exc)}
    device = {}
    if WITH_DEVICE:
        try:
            device = bench_device_supervisor()
        except Exception as exc:  # noqa: BLE001
            log(f"device-supervisor microbench FAILED: {exc!r}")
            device = {"error": repr(exc)}
    cluster_failover = {}
    if WITH_CLUSTER_FAILOVER:
        try:
            cluster_failover = bench_cluster_failover()
        except Exception as exc:  # noqa: BLE001
            log(f"cluster failover chaos FAILED: {exc!r}")
            cluster_failover = {"error": repr(exc)}
    swarm = {}
    if WITH_SWARM:
        try:
            swarm = bench_swarm()
        except Exception as exc:  # noqa: BLE001
            log(f"swarm harness FAILED: {exc!r}")
            swarm = {"error": repr(exc)}
    cluster_fanout = {}
    if WITH_CLUSTER_FANOUT:
        try:
            cluster_fanout = bench_cluster_fanout()
        except Exception as exc:  # noqa: BLE001
            log(f"cluster fanout bench FAILED: {exc!r}")
            cluster_fanout = {"error": repr(exc)}
    cluster_obs = {}
    if WITH_CLUSTER_OBS:
        try:
            cluster_obs = bench_cluster_obs()
        except Exception as exc:  # noqa: BLE001
            log(f"cluster obs bench FAILED: {exc!r}")
            cluster_obs = {"error": repr(exc)}
    slo = {}
    if WITH_SLO:
        try:
            slo = bench_slo()
        except Exception as exc:  # noqa: BLE001
            log(f"slo bench FAILED: {exc!r}")
            slo = {"error": repr(exc)}
    bigworld = {}
    if WITH_BIGWORLD:
        try:
            bigworld = bench_bigworld()
        except Exception as exc:  # noqa: BLE001
            log(f"bigworld bench FAILED: {exc!r}")
            bigworld = {"error": repr(exc)}
    federation = {}
    if WITH_FEDERATION:
        try:
            federation = bench_federation()
        except Exception as exc:  # noqa: BLE001
            log(f"federation bench FAILED: {exc!r}")
            federation = {"error": repr(exc)}

    n_check = min(E2E_ORACLE_JOBS, E2E_JOBS)
    parity_ok = same == n_check
    if not parity_ok:
        log(
            f"PARITY FAILURE: {same}/{n_check} — zeroing vs_baseline"
        )
    print(
        json.dumps(
            {
                "metric": "e2e_placements_per_sec_10k_nodes_binpack",
                "value": round(tpu_rate, 1),
                "unit": "placements/s",
                "vs_baseline": round(tpu_rate / oracle_rate, 2)
                if oracle_rate and parity_ok
                else 0.0,
                "p99_eval_latency_ms": round(p99, 1),
                "p50_eval_latency_ms": round(p50, 1),
                # offered-load vs p50/p99 curve (3 paced rates) with
                # flight-recorder trace-id exemplars at p99, so the
                # <250 ms tail-latency target is tracked per round
                "latency_sweep": sweep,
                "oracle_e2e_placements_per_sec": round(oracle_rate, 1),
                "parity_identical_evals": same,
                "e2e_stage_times_s": {
                    k: round(v, 3) for k, v in stage_times.items()
                },
                # the flight recorder's per-eval view of the same
                # stages (chunk spans divided by membership), cross-
                # checked against e2e_stage_times_s on stderr
                "e2e_trace_stage_times_s": {
                    k: round(v, 3) for k, v in trace_stages.items()
                },
                "trace_overhead_pct": trace_overhead,
                # placement explainability (A/B'd like the recorder)
                "explain_overhead_pct": explain_overhead,
                "e2e_prescore_share": round(prescore_share, 3),
                "e2e_replay_share": round(replay_share, 3),
                "replay_conflict_rate": round(
                    replay_conflict_rate, 3
                ),
                "replay_counters": replay_stats,
                "kernel_batch_placements_per_sec": round(
                    kernel.get("kernel-batch", 0.0), 1
                ),
                "kernel_chained_placements_per_sec": round(
                    kernel.get("kernel-chained", 0.0), 1
                ),
                "device_supervisor": device,
                # leadership-loss chaos: 5 leader kills + a healed
                # partition under load — per-kill detect-to-resume
                # times and the zero-lost/zero-duplicate verdicts
                "cluster_failover": cluster_failover,
                # follower scheduling fan-out: placements/s through
                # 1/3/5-server clusters on the same storm workload
                # (>=2x 3v1 acceptance) with zero-lost and
                # placement-set-parity verdicts
                "cluster_fanout": cluster_fanout,
                # cluster-scope observability: stitched-trace
                # overhead A/B on the fan-out path (<5% with
                # stitching engaged and zero orphans), leader
                # fan-in query latency at 1/3/5 servers, and the
                # metric history ring's full-depth footprint
                "cluster_obs": cluster_obs,
                # control-loop flight data: decision-ledger overhead
                # A/B (<3%), runtime site coverage under the swarm +
                # fan-out soak (the decision-ledger lint's
                # non-vacuity proof), and the SLO engine's burn-rate
                # grades over a real history ring
                "slo": slo,
                # million-node composed topology: fan-out followers
                # each heading a multi-process pod mesh over a
                # raft-seeded >=1M-node world (placements/s,
                # per-host bytes-per-flush, follower snapshot
                # catch-up time, zero-lost + pod digest parity)
                "bigworld": bigworld,
                # multi-region federation: two 3-server regions over
                # one WAN — cross-region forward latency, fan-out
                # registration latency, shed-redirect p99 and the
                # region-kill drill's detect/failover times
                # (wan_reads stays zero for region-local traffic)
                "federation": federation,
                # swarm-scale SLO harness: overload sheds + mass
                # node-death storm recovery against the real HTTP
                # API (zero lost / zero false downs / hb >=99.9% /
                # <=2 solves / p99 exemplars)
                "swarm": swarm,
                # global storm solver: mass-drain/scale-up replay
                # A/B'd storm-on vs storm-off (placements/s, solver
                # rounds, fallbacks, quality delta, zero-lost proof)
                "storm": storm,
                # policy-weighted scoring: fused-kernel overhead with
                # identity weights (<3% gate), heterogeneous-class
                # throughput capture A/B, and mass-replan migration
                # count A/B at equal-or-better aggregate score
                "policy": policy,
                # sharded hot-path proof: placements/s, per-device
                # HLO FLOPs, and host->device bytes/flush (delta vs
                # full) vs device count on the node-axis mesh
                "multichip": multichip,
                "configs": configs,
            }
        )
    )
    sys.stdout.flush()
    sys.stderr.flush()
    # hard-exit: daemon threads may sit inside XLA calls (background
    # compiles) and CPython teardown then aborts with "FATAL: exception
    # not rethrown"; the JSON is already out
    os._exit(0)


if __name__ == "__main__":
    main()
