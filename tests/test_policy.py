"""Policy-weighted scoring (sched/policy.py + ops/score.py
PolicyTerms): the fused kernel must stay bit-identical to the serial
weighted rank chain, to the policy-off kernel when no weights ride the
job, and to itself across every execution tier (single select, one-row
storm, node-sharded storm, fan-out follower).

Contracts under test:

- **Weighted parity** — a job carrying a PolicySpec (Gavel-style
  throughput-by-node-class table and/or migration-cost coefficient)
  places bit-identically through the vectorized kernel and the serial
  PolicyIterator oracle, AllocMetrics included (score_meta records the
  per-policy components on both sides).
- **Migration stickiness** — a destructive mass update of a placed job
  keeps every replacement on its incumbent node (the reschedule
  penalty drags every OTHER node's mean down), and stays
  oracle-parity while doing it.
- **Policy-off bit-identity** — NOMAD_TPU_POLICY=0 (or simply no
  spec) places exactly like a job with no policy: the None PolicyTerms
  contributes no pytree leaves, so the kernel trace is the policy-less
  build.
- **One-row storm parity** — a weighted eval forced through the storm
  solver (threshold 1) produces bit-identical placements, eval
  outcomes and AllocMetrics to the storm-off chain, strict replay on.
- **Sharded solve bit-identity** — the node-sharded weighted auction
  equals the single-device weighted solve in every output.
- **Fan-out followers** — followers assemble the same weight tensors
  from their own replicated state (zero new RPCs: the assembly reads
  only the job spec, node table and alloc index they already hold).
- **Tensor-cache invalidation** — the throughput-tensor cache turns
  over on job version bumps and node re-fingerprints
  (topo_generation), never serving a stale arena.
"""
import copy
import random
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.sched.generic_sched import ServiceScheduler
from nomad_tpu.structs import PolicySpec, compute_node_class


TPUT_TABLE = {"fast": 2.0, "slow": 1.0}


def policy_cluster(harness, n_nodes, seed=0, classes=("fast", "slow")):
    """Mixed-node-class cluster: every third node 'fast', ample
    resources so throughput weighting (not fit) decides placement."""
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.node_class = classes[0] if i % 3 == 0 else classes[1]
        n.node_resources.cpu = rng.choice([4000, 8000])
        n.node_resources.memory_mb = rng.choice([8192, 16384])
        n.attributes["rack"] = f"r{rng.randint(0, 4)}"
        n.computed_class = compute_node_class(n)
        harness.store.upsert_node(n)
        nodes.append(n)
    return nodes


def policy_job(tput=None, mig=0.0, count=6, cpu=500, mem=512, **kw):
    job = mock.job(**kw)
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].resources.cpu = cpu
    job.task_groups[0].tasks[0].resources.memory_mb = mem
    job.policy = PolicySpec(
        throughput=dict(tput or {}), migration_coefficient=mig
    )
    return job


def _plan_placements(harness):
    return sorted(
        (a.name, a.node_id)
        for v in harness.plans[-1].node_allocation.values()
        for a in v
    )


def _plan_score_meta(harness):
    """alloc name -> every scored node's (id, component scores, norm)
    — the AllocMetrics face of parity, policy.* components included."""
    out = {}
    for v in harness.plans[-1].node_allocation.values():
        for a in v:
            out[a.name] = sorted(
                (
                    m.node_id,
                    tuple(sorted(m.scores.items())),
                    m.norm_score,
                )
                for m in a.metrics.score_meta
            )
    return out


def run_both(harness, evaluation, seed):
    harness.reject_plan = True
    harness.process(
        ServiceScheduler, evaluation, use_tpu=False, seed=seed
    )
    oracle = (_plan_placements(harness), _plan_score_meta(harness))
    harness.process(
        ServiceScheduler, evaluation, use_tpu=True, seed=seed
    )
    tpu = (_plan_placements(harness), _plan_score_meta(harness))
    harness.reject_plan = False
    return oracle, tpu


def assert_identical(harness, evaluation, seed):
    (o_place, o_meta), (t_place, t_meta) = run_both(
        harness, evaluation, seed
    )
    assert o_place == t_place, (
        f"placements diverged:\n oracle={o_place}\n tpu={t_place}"
    )
    assert o_meta == t_meta, "AllocMetrics (score_meta) diverged"
    return o_place, o_meta


# ---------------------------------------------------------------------------
# weighted kernel vs serial weighted-rank oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("trial", range(4))
def test_throughput_weighted_parity(harness, trial):
    """Heterogeneity-aware throughput: vectorized weighted select ==
    serial PolicyIterator chain, placements and AllocMetrics, and the
    weights actually steer placement onto the fast class."""
    nodes = policy_cluster(harness, 36, seed=trial)
    job = policy_job(tput=TPUT_TABLE)
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    placements, meta = assert_identical(harness, ev, seed=trial * 7 + 1)
    assert len(placements) == 6
    class_of = {n.id: n.node_class for n in nodes}
    assert all(
        class_of[node_id] == "fast" for _, node_id in placements
    ), "throughput table did not steer placements to the fast class"
    # the explain decomposition records the throughput component for
    # every placed alloc's winner
    for rows in meta.values():
        assert any(
            "policy.throughput" in dict(scores)
            for _nid, scores, _norm in rows
        )


@pytest.mark.parametrize("trial", range(3))
def test_policy_with_affinity_and_spread_parity(harness, trial):
    """Policy terms append AFTER affinity/spread in the chain: the
    combined soft-score ordering must stay bit-identical."""
    from nomad_tpu.structs import Affinity, Spread, SpreadTarget

    policy_cluster(harness, 30, seed=trial + 50)
    job = policy_job(tput=TPUT_TABLE, mig=0.25, count=8)
    job.affinities = [Affinity("${attr.rack}", "r1", "=", 40)]
    job.spreads = [
        Spread(
            attribute="${attr.rack}",
            weight=30,
            targets=(SpreadTarget("r0", 60), SpreadTarget("r2", 40)),
        )
    ]
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    placements, _ = assert_identical(harness, ev, seed=trial * 5 + 2)
    assert len(placements) == 8


def test_migration_penalty_holds_incumbents_and_stays_parity(harness):
    """A destructive mass update (env bump) of a placed job: the
    migration penalty must keep every replacement on its incumbent
    node, bit-identically between kernel and oracle."""
    policy_cluster(harness, 24, seed=9)
    job = policy_job(tput=None, mig=0.5, count=6)
    job.task_groups[0].tasks[0].env = {"V": "1"}
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    harness.process(ServiceScheduler, ev, use_tpu=True, seed=3)
    incumbents = sorted(
        (a.name, a.node_id)
        for a in harness.store.allocs_by_job("default", job.id)
        if not a.terminal_status()
    )
    assert len(incumbents) == 6

    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].env = {"V": "2"}  # destructive
    harness.store.upsert_job(job2)
    ev2 = mock.evaluation(job_id=job.id)
    placements, _ = assert_identical(harness, ev2, seed=4)
    assert len(placements) == 6
    assert sorted(n for _, n in placements) == sorted(
        n for _, n in incumbents
    ), "migration penalty failed to hold the incumbent nodes"


def test_migration_zero_runtime_cutoff_fresh_placement(harness):
    """min_runtime_s in the future: no alloc is sticky yet, the
    migration group stays inert (None term) and parity holds."""
    policy_cluster(harness, 18, seed=11)
    job = policy_job(tput=TPUT_TABLE, mig=0.5, count=4)
    job.policy.min_runtime_s = 3600.0
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    placements, _ = assert_identical(harness, ev, seed=5)
    assert len(placements) == 4


# ---------------------------------------------------------------------------
# policy-off bit-identity
# ---------------------------------------------------------------------------


def test_policy_off_knob_matches_specless_job(harness, monkeypatch):
    """NOMAD_TPU_POLICY=0 with a spec'd job must place exactly like
    the same job with no spec at all — the kernel sees policy=None
    either way (same compiled signature, same trace)."""
    policy_cluster(harness, 30, seed=21)
    spec_job = policy_job(tput=TPUT_TABLE, mig=0.5, id="knob-a")
    bare_job = policy_job(tput=TPUT_TABLE, id="knob-b")
    bare_job.policy = None
    harness.store.upsert_job(spec_job)
    harness.store.upsert_job(bare_job)
    harness.reject_plan = True

    monkeypatch.setenv("NOMAD_TPU_POLICY", "0")
    harness.process(
        ServiceScheduler,
        mock.evaluation(job_id=spec_job.id),
        use_tpu=True,
        seed=6,
    )
    off_placements = _plan_placements(harness)
    off_meta = _plan_score_meta(harness)
    monkeypatch.delenv("NOMAD_TPU_POLICY")
    harness.process(
        ServiceScheduler,
        mock.evaluation(job_id=bare_job.id),
        use_tpu=True,
        seed=6,
    )
    bare_placements = _plan_placements(harness)
    assert sorted(n for _, n in off_placements) == sorted(
        n for _, n in bare_placements
    )
    # the disabled layer records NO policy components
    for rows in off_meta.values():
        for _nid, scores, _norm in rows:
            assert not any(
                k.startswith("policy.") for k, _v in dict(scores).items()
            )


def test_resolve_knob_overrides(monkeypatch):
    from nomad_tpu.sched.policy import resolve

    job = policy_job(tput=TPUT_TABLE, mig=0.5)
    pol = resolve(job)
    assert pol is not None
    assert pol.tput_coef == 1.0 and pol.mig_coef == 0.5
    # normalized by the table max, once, host-side
    assert pol.tput_value("fast") == 1.0
    assert pol.tput_value("slow") == 0.5
    assert pol.tput_value("unknown") == 0.0
    monkeypatch.setenv("NOMAD_TPU_POLICY_TPUT_COEF", "2.5")
    monkeypatch.setenv("NOMAD_TPU_POLICY_MIG_COEF", "0.75")
    pol = resolve(job)
    assert pol.tput_coef == 2.5 and pol.mig_coef == 0.75
    monkeypatch.setenv("NOMAD_TPU_POLICY", "0")
    assert resolve(job) is None


# ---------------------------------------------------------------------------
# one-row storm parity (strict replay)
# ---------------------------------------------------------------------------


def _storm_nodes(n, seed=3):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node(id=f"pol-storm-node-{seed}-{i:04d}")
        node.node_class = "fast" if i % 3 == 0 else "slow"
        node.node_resources.cpu = rng.choice([8000, 16000])
        node.node_resources.memory_mb = rng.choice([16384, 32768])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def _storm_policy_jobs(n, fam="polfam"):
    jobs = []
    for i in range(n):
        job = mock.job(id=f"{fam}/dispatch-{i:04d}")
        job.type = "batch"
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.cpu = 2000
        job.task_groups[0].tasks[0].resources.memory_mb = 4096
        job.policy = PolicySpec(throughput=dict(TPUT_TABLE))
        jobs.append(job)
    return jobs


def _run_storm_server(jobs, n_nodes=18, timeout=120):
    from nomad_tpu.server import Server

    server = Server(num_schedulers=1, seed=11, batch_pipeline=True)
    for node in _storm_nodes(n_nodes):
        server.register_node(copy.deepcopy(node))
    for job in jobs:
        server.register_job(copy.deepcopy(job))
    server.start()
    assert server.drain_to_idle(timeout)
    return server


def _placements(server, job_id):
    return sorted(
        (a.name, a.node_id)
        for a in server.store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    )


def _explain_metric(server, job_id, scores=True):
    """Comparable AllocMetric view from the explain ring.  With
    scores=False the score decomposition (ScoreMetaData + the
    placements' NormScore) is stripped: the storm replay re-verifies
    winners through a bare binpack pass and records the compact
    winner metric (batch_worker.py select), so soft-term score
    fidelity through the solver is compared only where the serial
    chain records the same compact shape."""
    from nomad_tpu.explain import EXPLAIN

    out = []
    for ev in sorted(
        server.store.evals_by_job("default", job_id),
        key=lambda e: e.create_index,
    ):
        rec = EXPLAIN.get(ev.id)
        if rec is None:
            out.append(None)
            continue
        tgs = {}
        for tg, entry in rec["TaskGroups"].items():
            metric = entry.get("Metric")
            if metric is not None:
                drop = {"AllocationTime"}
                if not scores:
                    drop.add("ScoreMetaData")
                metric = {
                    k: v
                    for k, v in metric.items()
                    if k not in drop
                }
            tgs[tg] = {
                "Placed": entry["Placed"],
                "Winner": entry["Winner"],
                "Placements": sorted(
                    (p["Name"], p["NodeID"])
                    + (
                        (round(p["NormScore"], 9),)
                        if scores
                        else ()
                    )
                    for p in entry["Placements"]
                ),
                "Metric": metric,
            }
        out.append(tgs)
    return out


def _eval_outcomes(server, job_id):
    return sorted(
        (
            e.status,
            e.status_description,
            tuple(sorted(e.queued_allocations.items())),
        )
        for e in server.store.evals_by_job("default", job_id)
    )


def test_one_row_weighted_storm_parity(monkeypatch):
    """A weighted eval forced through the storm solver (threshold 1,
    strict replay) is bit-identical to the storm-off weighted chain in
    placements and eval outcomes, and matches the serial metric modulo
    the score decomposition (the storm replay's winner re-verification
    records the compact binpack metric by design — batch_worker.py
    select — for weighted and affinity members alike).  The weighted
    unlimited walk still rides through the solver: NodesEvaluated on
    the storm side is every candidate, exactly as the serial chain
    with a resolved policy, and the serial side's full decomposition
    carries policy.throughput."""
    monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "1")
    jobs = _storm_policy_jobs(1, fam="poldegen")
    on = _run_storm_server(jobs)
    try:
        worker = on.workers[0]
        assert worker.storm_solves == 1, "solver did not engage"
        assert worker.storm_fallbacks == 0
        assert worker.storm_divergent == 0
        assert on.metrics.get_counter("policy.storm_evals") == 1
        on_place = _placements(on, jobs[0].id)
        on_out = _eval_outcomes(on, jobs[0].id)
        on_metric = _explain_metric(on, jobs[0].id, scores=False)
        # the resolved policy forced the unlimited walk through the
        # solver's pull accounting: every candidate evaluated
        evaluated = [
            entry["Metric"]["NodesEvaluated"]
            for tgs in on_metric
            if tgs
            for entry in tgs.values()
            if entry["Metric"]
        ]
        assert evaluated == [18], evaluated
        monkeypatch.setenv("NOMAD_TPU_STORM", "0")
        off = _run_storm_server(jobs)
        try:
            assert on_place == _placements(off, jobs[0].id)
            assert on_out == _eval_outcomes(off, jobs[0].id)
            assert on_metric == _explain_metric(
                off, jobs[0].id, scores=False
            )
            # the serial-equivalent chain records the per-policy
            # decomposition for every scored candidate
            off_full = _explain_metric(off, jobs[0].id, scores=True)
            winner_scores = [
                dict(sm.get("Scores") or {})
                for tgs in off_full
                if tgs
                for entry in tgs.values()
                if entry["Metric"]
                for sm in entry["Metric"]["ScoreMetaData"]
            ]
            assert winner_scores and all(
                "policy.throughput" in s for s in winner_scores
            ), winner_scores
        finally:
            off.stop()
    finally:
        on.stop()


def test_mass_weighted_storm_places_on_fast_class(monkeypatch):
    """A weighted family storm: the fused per-eval rows steer every
    solver placement onto the fast class, zero lost."""
    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "6")
    jobs = _storm_policy_jobs(12, fam="polmass")
    server = _run_storm_server(jobs, n_nodes=24)
    try:
        worker = server.workers[0]
        assert worker.storm_evals == 12
        class_of = {
            n.id: n.node_class for n in _storm_nodes(24)
        }
        placed = []
        for job in jobs:
            p = _placements(server, job.id)
            assert len(p) == 1
            placed.extend(p)
        fast = sum(
            1 for _, nid in placed if class_of[nid] == "fast"
        )
        # 8 fast nodes x 8000+ cpu hold all 12 x 2000cpu asks
        assert fast == 12, f"only {fast}/12 on the fast class"
        for job in jobs:
            evs = server.store.evals_by_job("default", job.id)
            assert all(e.terminal_status() for e in evs)
        assert server.broker.failed() == []
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# sharded weighted solve == single-device weighted solve
# ---------------------------------------------------------------------------


def _mesh8():
    from nomad_tpu.parallel.mesh import make_mesh

    return make_mesh(8, eval_axis=1)


def _weighted_storm_problem(E, A, C, seed=0, limit=2):
    from nomad_tpu.ops.solve import StormInputs

    rng = np.random.default_rng(seed)
    perm = np.stack(
        [rng.permutation(C).astype(np.int32) for _ in range(E)]
    )
    # mixed storm: some evals weighted (throughput and/or migration),
    # some policy-less (all-zero rows — the float-exact no-op)
    has_tput = (rng.random(E) > 0.3).astype(np.float64)
    tput_term = np.where(
        has_tput[:, None] > 0, 0.8 * rng.random((E, C)), 0.0
    )
    mig_term = np.where(
        rng.random((E, C)) > 0.7, -0.5, 0.0
    ) * (rng.random(E) > 0.5)[:, None]
    inp = StormInputs(
        feasible=rng.random((E, C)) > 0.15,
        affinity=np.where(
            rng.random((E, C)) > 0.8, rng.random((E, C)), 0.0
        ),
        collisions=(rng.random((E, C)) > 0.9).astype(np.int32),
        perm=perm,
        limit=np.full(E, limit, np.int32),
        n_cand=np.full(E, C, np.int32),
        eval_of=(np.arange(A) % E).astype(np.int32),
        penalty=rng.random((A, C)) > 0.95,
        ask=np.tile(
            np.asarray((100.0, 100.0, 100.0), np.float64), (A, 1)
        ),
        desired=np.ones(A, np.int32),
        real=np.ones(A, bool),
        pre_cpu=np.zeros(C),
        pre_mem=np.zeros(C),
        pre_disk=np.zeros(C),
        policy_tput_term=tput_term,
        policy_has_tput=has_tput,
        policy_mig_term=mig_term,
    )
    cols = tuple(
        np.asarray(x, np.float64)
        for x in (
            np.full(C, 4000.0),
            np.full(C, 8192.0),
            np.full(C, 100000.0),
            rng.integers(0, 2000, C).astype(np.float64),
            rng.integers(0, 4096, C).astype(np.float64),
            np.zeros(C),
        )
    )
    return inp, cols


@pytest.mark.parametrize(
    "E,A,C,seed",
    [
        (8, 32, 64, 3),
        (4, 8, 128, 9),
        (1, 1, 16, 7),  # degenerate weighted one-row storm
    ],
)
def test_sharded_weighted_storm_bit_identical(E, A, C, seed):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nomad_tpu.ops.solve import (
        storm_assignment,
        storm_assignment_sharded,
    )
    from nomad_tpu.sched.storm import stage_for_mesh

    inp, cols = _weighted_storm_problem(E, A, C, seed=seed)
    single = storm_assignment(
        inp, cols, spread_fit=False, max_rounds=A
    )
    mesh = _mesh8()
    sharded = storm_assignment_sharded(
        mesh, spread_fit=False, max_rounds=A, weighted=True
    )(
        stage_for_mesh(inp, mesh),
        tuple(
            jax.device_put(c, NamedSharding(mesh, P("nodes")))
            for c in cols
        ),
    )
    names = (
        "assigned", "pulls", "acc_round", "score", "greedy", "rounds"
    )
    for name, s, m in zip(names, single, sharded):
        assert np.array_equal(np.asarray(s), np.asarray(m)), (
            f"sharded weighted storm diverged in {name}"
        )


# ---------------------------------------------------------------------------
# fan-out followers assemble from replicated state
# ---------------------------------------------------------------------------


def test_fanout_follower_assembles_policy_from_replicated_state(
    monkeypatch,
):
    """A 3-server fan-out cluster placing weighted jobs matches the
    single-server oracle's live placement set AND its policy outcome
    (every placement steered onto the fast class), and the policy
    tensors were assembled on the follower(s) from their own
    replicated store — the policy.* series move on a non-leader
    server, with zero policy-specific RPCs (there are none to call)."""
    from tests.test_fanout import _live_placements, wait_until

    from nomad_tpu.server import Server
    from nomad_tpu.server.cluster import TestCluster

    n_jobs = 18
    nodes = _storm_nodes(12, seed=5)
    class_of = {n.id: n.node_class for n in nodes}
    jobs = []
    for i in range(n_jobs):
        job = policy_job(
            tput=TPUT_TABLE, count=1, cpu=1000, mem=1024,
            id=f"pol-fo-{i:04d}",
        )
        jobs.append(job)

    def _live_nodes(store):
        return sorted(
            (a.job_id, a.name, class_of[a.node_id])
            for a in store.allocs.values()
            if not a.terminal_status()
        )

    oracle = Server(num_schedulers=1, seed=0, batch_pipeline=True)
    oracle.start()
    try:
        for node in nodes:
            oracle.register_node(copy.deepcopy(node))
        for job in jobs:
            oracle.register_job(copy.deepcopy(job))
        assert oracle.drain_to_idle(timeout=60.0)
        want = _live_placements(oracle.store)
        want_classes = _live_nodes(oracle.store)
        assert oracle.metrics.get_counter("policy.evals") > 0
    finally:
        oracle.stop()
    assert len(want) == n_jobs
    assert all(cls == "fast" for _j, _n, cls in want_classes), (
        "oracle did not steer onto the fast class"
    )

    monkeypatch.setenv("NOMAD_TPU_FANOUT", "1")
    cluster = TestCluster(3, heartbeat_ttl=300.0)
    cluster.start()
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        for node in nodes:
            leader.register_node(copy.deepcopy(node))
        for i, job in enumerate(jobs):
            cluster.servers[i % 3].register_job(copy.deepcopy(job))
        wait_until(
            lambda: len(
                _live_placements(
                    cluster.wait_for_leader(timeout=30.0).store
                )
            )
            == n_jobs
            and cluster.wait_for_leader(timeout=30.0).drain_to_idle(
                timeout=1.0
            ),
            timeout=90.0,
            msg="fan-out drain",
        )
        leader = cluster.wait_for_leader(timeout=30.0)
        assert _live_placements(leader.store) == want
        # same policy outcome as the oracle: the fan-out followers'
        # weighted walks landed every placement on the fast class
        assert _live_nodes(leader.store) == want_classes
        follower_plans = sum(
            s.metrics.get_counter("fanout.plans_submitted")
            for s in cluster.servers
        )
        assert follower_plans > 0, "fan-out never engaged"
        follower_policy_evals = sum(
            s.metrics.get_counter("policy.evals")
            + s.metrics.get_counter("policy.storm_evals")
            for s in cluster.servers
            if not s.is_leader()
        )
        assert follower_policy_evals > 0, (
            "no follower ever assembled policy tensors from its "
            "replicated state"
        )
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# tensor cache invalidation
# ---------------------------------------------------------------------------


def test_tput_tensor_cache_turnover(harness):
    from nomad_tpu.sched.policy import (
        clear_tput_cache,
        migration_vector,
        resolve,
        tput_tensor,
    )

    nodes = policy_cluster(harness, 12, seed=31)
    table = harness.snapshot().node_table
    job = policy_job(tput=TPUT_TABLE)
    pol = resolve(job)
    clear_tput_cache()

    t1 = tput_tensor(pol, job, table)
    t2 = tput_tensor(pol, job, table)
    assert t2 is t1, "warm assembly must be a cache hit"
    # values follow the interned node.class column
    for n in nodes:
        row = table.row_of[n.id]
        want = 1.0 if n.node_class == "fast" else 0.5
        assert t1[row] == want

    # job version bump (spec update) -> new tensor
    job_v2 = copy.deepcopy(job)
    job_v2.version = job.version + 1
    t3 = tput_tensor(pol, job_v2, table)
    assert t3 is not t1

    # node re-fingerprint: class change bumps topo_generation and
    # invalidates — the stale arena is never served
    gen0 = table.topo_generation
    flipped = copy.deepcopy(nodes[1])
    flipped.node_class = "fast"
    harness.store.upsert_node(flipped)
    table2 = harness.snapshot().node_table
    assert table2.topo_generation > gen0
    t4 = tput_tensor(pol, job, table2)
    assert t4 is not t1
    assert t4[table2.row_of[flipped.id]] == 1.0

    clear_tput_cache()
    t5 = tput_tensor(pol, job, table2)
    assert t5 is not t4
    np.testing.assert_array_equal(np.asarray(t5), np.asarray(t4))


def test_migration_vector_shape(harness):
    """Penalty semantics: -1 everywhere EXCEPT the sticky rows, and
    all-zero (inert) when the sticky set is empty — a bonus on the
    incumbent would backfire under mean-of-components scoring."""
    from nomad_tpu.sched.policy import migration_vector

    nodes = policy_cluster(harness, 8, seed=41)
    table = harness.snapshot().node_table
    assert not migration_vector(set(), table).any()
    sticky = {nodes[2].id, nodes[5].id}
    mig = migration_vector(sticky, table)
    for n in nodes:
        row = table.row_of[n.id]
        assert mig[row] == (0.0 if n.id in sticky else -1.0)
