"""Gossip membership + region federation tests (reference: serf
membership in nomad/serf.go, region forwarding in nomad/rpc.go:645)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft.transport import InmemTransport
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.server.membership import ALIVE, DEAD, LEFT, Gossip


def wait_until(pred, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def make_pool(n, transport=None, **kw):
    transport = transport or InmemTransport()
    pool = []
    for i in range(n):
        g = Gossip(f"g{i}", f"g{i}", transport, **kw)
        transport.register(g.addr, lambda m, p, g=g: g.handle(m, p))
        pool.append(g)
    for g in pool:
        g.start()
    for g in pool[1:]:
        g.join(pool[0].addr)
    return transport, pool


def test_pool_converges_to_full_membership():
    _, pool = make_pool(4)
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 4 for g in pool),
            msg="membership convergence",
        )
        for g in pool:
            assert sorted(m.name for m in g.alive_members()) == [
                "g0", "g1", "g2", "g3",
            ]
    finally:
        for g in pool:
            g.stop()


def test_failed_member_detected():
    transport, pool = make_pool(4, suspicion_timeout=0.4)
    events = []
    for g in pool:
        g.on_event = lambda kind, m, g=g: events.append(
            (g.name, kind, m.name)
        )
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 4 for g in pool)
        )
        victim = pool[-1]
        victim.stop()
        transport.set_down(victim.addr)
        rest = pool[:-1]
        wait_until(
            lambda: all(
                g.members[victim.name].status == DEAD for g in rest
            ),
            msg="failure detection",
        )
        assert any(
            kind == "member-failed" and name == victim.name
            for _, kind, name in events
        )
    finally:
        for g in pool[:-1]:
            g.stop()


def test_graceful_leave_is_not_a_failure():
    _, pool = make_pool(3)
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 3 for g in pool)
        )
        leaver = pool[-1]
        leaver.leave()
        rest = pool[:-1]
        wait_until(
            lambda: all(
                g.members[leaver.name].status == LEFT for g in rest
            ),
            msg="leave propagation",
        )
    finally:
        for g in pool[:-1]:
            g.stop()


def test_rejoin_after_graceful_leave():
    """A member that left can come back with a fresh incarnation 0 and
    refute the stale LEFT rumor (serf rejoin semantics)."""
    transport, pool = make_pool(3)
    reborn = None
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 3 for g in pool)
        )
        leaver = pool[-1]
        leaver.leave()
        leaver.stop()
        rest = pool[:-1]
        wait_until(
            lambda: all(
                g.members[leaver.name].status == LEFT for g in rest
            ),
            msg="leave propagation",
        )
        # same name/addr, brand-new process: incarnation restarts at 0
        reborn = Gossip(leaver.name, leaver.addr, transport)
        transport.register(
            reborn.addr, lambda m, p: reborn.handle(m, p)
        )
        reborn.start()
        reborn.join(pool[0].addr)
        wait_until(
            lambda: all(
                g.members[leaver.name].status == ALIVE for g in rest
            ),
            msg="rejoin refutes stale LEFT",
        )
    finally:
        for g in pool[:-1]:
            g.stop()
        if reborn is not None:
            reborn.stop()


def test_refutation_revives_falsely_suspected_member():
    transport, pool = make_pool(3, suspicion_timeout=0.3)
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 3 for g in pool)
        )
        victim = pool[-1]
        # partition victim briefly so peers mark it dead
        transport.isolate(victim.addr)
        wait_until(
            lambda: pool[0].members[victim.name].status == DEAD,
            msg="false death",
        )
        transport.heal()
        wait_until(
            lambda: all(
                g.members[victim.name].status == ALIVE for g in pool
            ),
            msg="refutation",
        )
        # the refuted incarnation outbids the death rumor
        assert victim.members[victim.name].incarnation > 0
    finally:
        for g in pool:
            g.stop()


@pytest.fixture
def federation():
    transport = InmemTransport()
    east = TestCluster(
        3, transport=transport, region="east", name_prefix="east",
        heartbeat_ttl=60.0,
    )
    west = TestCluster(
        3, transport=transport, region="west", name_prefix="west",
        heartbeat_ttl=60.0,
    )
    east.start()
    west.start()
    # WAN join: bridge the two regional pools
    east.servers[0].join(west.servers[0].addr)
    yield east, west
    east.stop()
    west.stop()


def test_cross_region_job_submission(federation):
    east, west = federation
    east_leader = east.wait_for_leader()
    west_leader = west.wait_for_leader()
    wait_until(
        lambda: len(east_leader.gossip.members_in_region("west")) == 3,
        msg="WAN membership convergence",
    )
    for _ in range(3):
        west_leader.register_node(mock.node())

    job = mock.job(id="west-job")
    job.region = "west"
    # submitted via an EAST server: must hop to west and schedule there
    east.servers[1].register_job(job)
    assert west_leader.drain_to_idle(timeout=10.0)
    assert len(west_leader.store.allocs_by_job("default", "west-job")) == 10
    assert east_leader.store.job_by_id("default", "west-job") is None


def test_default_region_job_resolves_to_local_region(federation):
    """A job that never named a region (struct default "global") must
    register in the receiving server's region, not fail with
    'no path to region' (reference: agent resolves empty region)."""
    east, west = federation
    east_leader = east.wait_for_leader()
    for _ in range(2):
        east_leader.register_node(mock.node())
    job = mock.job(id="regionless-job")
    assert job.region == "global"
    east.servers[1].register_job(job)
    assert east_leader.drain_to_idle(timeout=10.0)
    stored = east_leader.store.job_by_id("default", "regionless-job")
    assert stored is not None
    assert stored.region == "east"


def test_regions_listing(federation):
    east, west = federation
    leader = east.wait_for_leader()
    wait_until(
        lambda: {m.region for m in leader.gossip.alive_members()}
        == {"east", "west"},
        msg="region discovery",
    )
    members = leader.server_members()
    assert len(members) == 6


# -- member wire records (http_addr rides the gossip) -----------------


def test_member_record_round_trip_with_http_addr():
    """record() -> _merge() round-trips every field, including the
    HTTP advertise address federation redirects are built from."""
    from nomad_tpu.server.membership import Member

    src = Member(
        "m1", "addr1", region="east", role="server",
        incarnation=3, status=ALIVE, http_addr="127.0.0.1:4646",
    )
    rec = src.record()
    assert rec[-1] == "127.0.0.1:4646"

    sink = Gossip("g0", "g0", InmemTransport())
    sink._merge([rec])
    got = sink.members["m1"]
    assert (got.name, got.addr, got.region, got.role) == (
        "m1", "addr1", "east", "server",
    )
    assert got.incarnation == 3
    assert got.http_addr == "127.0.0.1:4646"


def test_member_merge_tolerates_legacy_six_tuple():
    """A pre-http_addr peer gossips 6-tuples; a mixed-version pool
    must still converge (http_addr stays empty, never a crash)."""
    sink = Gossip("g0", "g0", InmemTransport())
    sink._merge([("old", "old-addr", "west", "server", 1, ALIVE)])
    got = sink.members["old"]
    assert got.status == ALIVE
    assert got.http_addr == ""
    # a later 7-tuple from an upgraded peer fills the field in
    sink._merge(
        [("old", "old-addr", "west", "server", 2, ALIVE, "h:1")]
    )
    assert sink.members["old"].http_addr == "h:1"


def test_advertise_http_bumps_incarnation_and_spreads():
    """advertise_http must outbid equal-incarnation cached views: the
    bump makes the new field win the rumor race pool-wide."""
    _, pool = make_pool(3)
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 3 for g in pool)
        )
        inc_before = pool[0].members["g0"].incarnation
        pool[0].advertise_http("127.0.0.1:4646")
        assert pool[0].members["g0"].incarnation == inc_before + 1
        wait_until(
            lambda: all(
                g.members["g0"].http_addr == "127.0.0.1:4646"
                for g in pool
            ),
            msg="http advertise rumor spread",
        )
        listed = {
            m["Name"]: m["HTTPAddr"] for m in pool[-1].member_list()
        }
        assert listed["g0"] == "127.0.0.1:4646"
    finally:
        for g in pool:
            g.stop()


# -- members_in_region under churn ------------------------------------


def make_region_pool(regions, transport=None, **kw):
    """One gossip pool spanning several regions (the WAN shape)."""
    transport = transport or InmemTransport()
    pool = []
    for i, region in enumerate(regions):
        g = Gossip(
            f"r{i}", f"r{i}", transport, region=region, **kw
        )
        transport.register(g.addr, lambda m, p, g=g: g.handle(m, p))
        pool.append(g)
    for g in pool:
        g.start()
    for g in pool[1:]:
        g.join(pool[0].addr)
    return transport, pool


@pytest.mark.parametrize("churn", ["died", "left"])
def test_members_in_region_all_gone_is_empty(churn):
    """A region whose members all churned out must resolve to an
    EMPTY routing table — stale ALIVE entries here would aim
    cross-region forwards (and shed redirects) at a dead region."""
    transport, pool = make_region_pool(
        ["a", "a", "b", "b"], suspicion_timeout=0.3
    )
    observers = pool[:2]
    b_members = pool[2:]
    try:
        wait_until(
            lambda: all(
                len(g.members_in_region("b")) == 2 for g in observers
            ),
            msg="region b discovered",
        )
        if churn == "left":
            for g in b_members:
                g.leave()
        else:
            for g in b_members:
                transport.isolate(g.addr)
        wait_until(
            lambda: all(
                g.members_in_region("b") == [] for g in observers
            ),
            msg="region b emptied",
        )
        # region a is untouched by b's churn
        assert all(
            len(g.members_in_region("a")) == 2 for g in observers
        )
    finally:
        for g in pool:
            g.stop()


def test_members_in_region_refutation_restores():
    """A falsely-dead region refutes the rumor and returns to the
    routing table — forwards resume without operator action."""
    transport, pool = make_region_pool(
        ["a", "a", "b"], suspicion_timeout=0.3
    )
    observer, b_member = pool[0], pool[-1]
    try:
        wait_until(
            lambda: len(observer.members_in_region("b")) == 1,
            msg="region b discovered",
        )
        transport.isolate(b_member.addr)
        wait_until(
            lambda: observer.members_in_region("b") == [],
            msg="region b falsely dead",
        )
        transport.heal()
        wait_until(
            lambda: len(observer.members_in_region("b")) == 1,
            msg="region b refuted back",
        )
        assert observer.members[b_member.name].incarnation > 0
    finally:
        for g in pool:
            g.stop()
