"""Gossip membership + region federation tests (reference: serf
membership in nomad/serf.go, region forwarding in nomad/rpc.go:645)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft.transport import InmemTransport
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.server.membership import ALIVE, DEAD, LEFT, Gossip


def wait_until(pred, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def make_pool(n, transport=None, **kw):
    transport = transport or InmemTransport()
    pool = []
    for i in range(n):
        g = Gossip(f"g{i}", f"g{i}", transport, **kw)
        transport.register(g.addr, lambda m, p, g=g: g.handle(m, p))
        pool.append(g)
    for g in pool:
        g.start()
    for g in pool[1:]:
        g.join(pool[0].addr)
    return transport, pool


def test_pool_converges_to_full_membership():
    _, pool = make_pool(4)
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 4 for g in pool),
            msg="membership convergence",
        )
        for g in pool:
            assert sorted(m.name for m in g.alive_members()) == [
                "g0", "g1", "g2", "g3",
            ]
    finally:
        for g in pool:
            g.stop()


def test_failed_member_detected():
    transport, pool = make_pool(4, suspicion_timeout=0.4)
    events = []
    for g in pool:
        g.on_event = lambda kind, m, g=g: events.append(
            (g.name, kind, m.name)
        )
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 4 for g in pool)
        )
        victim = pool[-1]
        victim.stop()
        transport.set_down(victim.addr)
        rest = pool[:-1]
        wait_until(
            lambda: all(
                g.members[victim.name].status == DEAD for g in rest
            ),
            msg="failure detection",
        )
        assert any(
            kind == "member-failed" and name == victim.name
            for _, kind, name in events
        )
    finally:
        for g in pool[:-1]:
            g.stop()


def test_graceful_leave_is_not_a_failure():
    _, pool = make_pool(3)
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 3 for g in pool)
        )
        leaver = pool[-1]
        leaver.leave()
        rest = pool[:-1]
        wait_until(
            lambda: all(
                g.members[leaver.name].status == LEFT for g in rest
            ),
            msg="leave propagation",
        )
    finally:
        for g in pool[:-1]:
            g.stop()


def test_rejoin_after_graceful_leave():
    """A member that left can come back with a fresh incarnation 0 and
    refute the stale LEFT rumor (serf rejoin semantics)."""
    transport, pool = make_pool(3)
    reborn = None
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 3 for g in pool)
        )
        leaver = pool[-1]
        leaver.leave()
        leaver.stop()
        rest = pool[:-1]
        wait_until(
            lambda: all(
                g.members[leaver.name].status == LEFT for g in rest
            ),
            msg="leave propagation",
        )
        # same name/addr, brand-new process: incarnation restarts at 0
        reborn = Gossip(leaver.name, leaver.addr, transport)
        transport.register(
            reborn.addr, lambda m, p: reborn.handle(m, p)
        )
        reborn.start()
        reborn.join(pool[0].addr)
        wait_until(
            lambda: all(
                g.members[leaver.name].status == ALIVE for g in rest
            ),
            msg="rejoin refutes stale LEFT",
        )
    finally:
        for g in pool[:-1]:
            g.stop()
        if reborn is not None:
            reborn.stop()


def test_refutation_revives_falsely_suspected_member():
    transport, pool = make_pool(3, suspicion_timeout=0.3)
    try:
        wait_until(
            lambda: all(len(g.alive_members()) == 3 for g in pool)
        )
        victim = pool[-1]
        # partition victim briefly so peers mark it dead
        transport.isolate(victim.addr)
        wait_until(
            lambda: pool[0].members[victim.name].status == DEAD,
            msg="false death",
        )
        transport.heal()
        wait_until(
            lambda: all(
                g.members[victim.name].status == ALIVE for g in pool
            ),
            msg="refutation",
        )
        # the refuted incarnation outbids the death rumor
        assert victim.members[victim.name].incarnation > 0
    finally:
        for g in pool:
            g.stop()


@pytest.fixture
def federation():
    transport = InmemTransport()
    east = TestCluster(
        3, transport=transport, region="east", name_prefix="east",
        heartbeat_ttl=60.0,
    )
    west = TestCluster(
        3, transport=transport, region="west", name_prefix="west",
        heartbeat_ttl=60.0,
    )
    east.start()
    west.start()
    # WAN join: bridge the two regional pools
    east.servers[0].join(west.servers[0].addr)
    yield east, west
    east.stop()
    west.stop()


def test_cross_region_job_submission(federation):
    east, west = federation
    east_leader = east.wait_for_leader()
    west_leader = west.wait_for_leader()
    wait_until(
        lambda: len(east_leader.gossip.members_in_region("west")) == 3,
        msg="WAN membership convergence",
    )
    for _ in range(3):
        west_leader.register_node(mock.node())

    job = mock.job(id="west-job")
    job.region = "west"
    # submitted via an EAST server: must hop to west and schedule there
    east.servers[1].register_job(job)
    assert west_leader.drain_to_idle(timeout=10.0)
    assert len(west_leader.store.allocs_by_job("default", "west-job")) == 10
    assert east_leader.store.job_by_id("default", "west-job") is None


def test_default_region_job_resolves_to_local_region(federation):
    """A job that never named a region (struct default "global") must
    register in the receiving server's region, not fail with
    'no path to region' (reference: agent resolves empty region)."""
    east, west = federation
    east_leader = east.wait_for_leader()
    for _ in range(2):
        east_leader.register_node(mock.node())
    job = mock.job(id="regionless-job")
    assert job.region == "global"
    east.servers[1].register_job(job)
    assert east_leader.drain_to_idle(timeout=10.0)
    stored = east_leader.store.job_by_id("default", "regionless-job")
    assert stored is not None
    assert stored.region == "east"


def test_regions_listing(federation):
    east, west = federation
    leader = east.wait_for_leader()
    wait_until(
        lambda: {m.region for m in leader.gossip.alive_members()}
        == {"east", "west"},
        msg="region discovery",
    )
    members = leader.server_members()
    assert len(members) == 6
