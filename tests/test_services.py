"""Service catalog + template/secrets tests (reference model:
command/agent/consul tests, taskrunner/template tests).
"""
import json
import socket
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client
from nomad_tpu.client.templates import (
    FileSecretsProvider,
    StaticSecretsProvider,
    TemplateError,
    render_template,
)
from nomad_tpu.server import Server
from nomad_tpu.structs import (
    AllocatedSharedResources,
    AssignedPortData,
    Service,
    Task,
)


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# templates / secrets
# ---------------------------------------------------------------------------


def test_render_env_and_meta():
    out = render_template(
        'addr={{ env "ADDR" }} region={{ meta "region" }}',
        env={"ADDR": "1.2.3.4"},
        meta={"region": "us"},
    )
    assert out == "addr=1.2.3.4 region=us"


def test_render_secrets():
    secrets = StaticSecretsProvider(
        {"db/creds": {"user": "app", "password": "hunter2"}}
    )
    out = render_template(
        'u={{ secret "db/creds" "user" }} p={{ secret "db/creds" "password" }}',
        secrets=secrets,
    )
    assert out == "u=app p=hunter2"
    with pytest.raises(TemplateError):
        render_template('{{ secret "nope" "x" }}', secrets=secrets)
    with pytest.raises(TemplateError):
        render_template('{{ secret "db/creds" "nope" }}', secrets=secrets)


def test_file_secrets_provider(tmp_path):
    d = tmp_path / "db"
    d.mkdir()
    (d / "creds.json").write_text(json.dumps({"user": "filed"}))
    provider = FileSecretsProvider(str(tmp_path))
    assert provider.read("db/creds")["user"] == "filed"
    assert provider.read("../etc/passwd") is None
    assert provider.read("missing") is None


# ---------------------------------------------------------------------------
# catalog
# ---------------------------------------------------------------------------


@pytest.fixture
def server():
    s = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=88)
    s.start()
    yield s
    s.stop()


def _service_job(job_id="svc", port_label="http", count=2):
    job = mock.job(id=job_id)
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0] = Task(
        name="web",
        driver="mock_driver",
        config={"run_for": -1},
        services=[
            Service(name="web-api", port_label=port_label, tags=["v1"])
        ],
    )
    return job


def test_catalog_tracks_running_allocs(server):
    for _ in range(3):
        server.register_node(mock.node())
    client = None
    job = _service_job()
    server.register_job(job)
    assert server.drain_to_idle(10)
    server.catalog.sync()
    # allocs pending: registered but unhealthy
    instances = server.catalog.instances("web-api")
    assert len(instances) == 2
    assert all(not i.healthy for i in instances)

    # mark running -> healthy
    allocs = server.store.allocs_by_job("default", job.id)
    for a in allocs:
        a.client_status = "running"
        # give one a port
        if a.allocated_resources:
            a.allocated_resources.shared.ports = [
                AssignedPortData(label="http", value=8080)
            ]
    server.store.upsert_allocs(allocs)
    server.catalog.sync()
    healthy = server.catalog.instances("web-api", healthy_only=True)
    assert len(healthy) == 2
    assert any(i.port == 8080 for i in healthy)
    assert server.catalog.services() == ["web-api"]

    # stop -> deregistered
    server.deregister_job("default", job.id)
    assert server.drain_to_idle(10)
    server.catalog.sync()
    assert server.catalog.instances("web-api") == []


def test_catalog_check_status_folds_into_health(server):
    server.register_node(mock.node())
    job = _service_job(count=1)
    server.register_job(job)
    assert server.drain_to_idle(10)
    allocs = server.store.allocs_by_job("default", job.id)
    for a in allocs:
        a.client_status = "running"
    server.store.upsert_allocs(allocs)
    server.catalog.sync()
    assert server.catalog.instances("web-api", healthy_only=True)
    server.catalog.set_check_status(
        allocs[0].id, "web", "web-api", False
    )
    assert not server.catalog.instances("web-api", healthy_only=True)


def test_tcp_check_runner(server):
    # a real listening socket the check can hit
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    try:
        job = mock.job(id="checked")
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0] = Task(
            name="web",
            driver="mock_driver",
            config={"run_for": -1},
            services=[
                Service(
                    name="checked-svc",
                    checks=[{"type": "tcp", "port": port}],
                )
            ],
        )
        client = Client(
            server, node=mock.node(), fingerprint=False
        )
        client.start()
        try:
            server.register_job(job)
            assert server.drain_to_idle(10)
            assert wait_until(
                lambda: server.catalog.instances(
                    "checked-svc", healthy_only=True
                ),
                timeout=10,
            )
            # kill the listener: check fails, instance goes unhealthy
            listener.close()
            assert wait_until(
                lambda: not server.catalog.instances(
                    "checked-svc", healthy_only=True
                ),
                timeout=10,
            )
        finally:
            client.stop()
    finally:
        try:
            listener.close()
        except OSError:
            pass


def test_template_rendering_into_alloc_dir(server, tmp_path):
    secrets = StaticSecretsProvider({"app/conf": {"token": "s3cr3t"}})
    client = Client(
        server,
        node=mock.node(),
        data_dir=str(tmp_path),
        fingerprint=False,
        secrets=secrets,
    )
    client.start()
    try:
        job = mock.job(id="templated")
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0] = Task(
            name="web",
            driver="mock_driver",
            config={"run_for": -1},
            templates=[
                {
                    "destination": "local/app.conf",
                    "data": 'token={{ secret "app/conf" "token" }}\n'
                            'alloc={{ env "NOMAD_ALLOC_ID" }}\n',
                }
            ],
        )
        server.register_job(job)
        assert server.drain_to_idle(10)
        allocs = server.store.allocs_by_job("default", "templated")
        path = tmp_path / "allocs" / allocs[0].id / "local" / "app.conf"
        assert wait_until(lambda: path.exists(), timeout=10)
        content = path.read_text()
        assert "token=s3cr3t" in content
        assert f"alloc={allocs[0].id}" in content
    finally:
        client.stop()
