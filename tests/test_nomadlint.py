"""Tier-1 wiring of tools/nomadlint — the pluggable AST analysis
suite.  Every registered rule must trip on its bad fixture and stay
quiet on its clean fixture, and a repo-wide run must report zero
unsuppressed findings (suppressions must carry justifications)."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.nomadlint import Context, all_rules, run  # noqa: E402
from tools.nomadlint.rules import MIGRATED_RULES  # noqa: E402


def _ctx():
    return Context(REPO)


def test_rule_inventory():
    """11 migrated stage-accounting rules + the 4 new passes."""
    names = [cls.name for cls in all_rules()]
    assert len(names) == len(set(names))
    for migrated in MIGRATED_RULES:
        assert migrated in names
    for new in (
        "donation-safety",
        "jit-purity",
        "lock-discipline",
        "config-drift",
    ):
        assert new in names
    assert len(names) >= 15


def test_repo_wide_run_is_clean():
    """The acceptance gate: zero unsuppressed findings on the live
    tree with all rules active."""
    result = run(_ctx())
    assert result.ok, [
        f.render(REPO) for f in result.findings
    ]
    # the two documented, justified suppressions (mirror-sync
    # donation + per-probe canary retrace) are present and applied
    assert len(result.suppressed) >= 2
    rules = {f.rule for f in result.suppressed}
    assert "donation-safety" in rules
    assert "jit-purity" in rules


def test_every_rule_trips_its_bad_fixture(tmp_path):
    ctx = _ctx()
    for cls in all_rules():
        bad_ctx = cls.bad_fixture(ctx, str(tmp_path))
        findings = cls().check(bad_ctx)
        assert findings, f"rule {cls.name} missed its bad fixture"
        assert all(f.rule == cls.name for f in findings)


def test_every_rule_passes_its_clean_fixture(tmp_path):
    ctx = _ctx()
    for cls in all_rules():
        clean_ctx = cls.clean_fixture(ctx, str(tmp_path))
        if clean_ctx is ctx:
            continue  # live repo: covered by the repo-wide run
        findings = cls().check(clean_ctx)
        assert not findings, (
            f"rule {cls.name} tripped on its clean fixture: "
            f"{findings[0].message}"
        )


def test_suppression_hides_finding_and_requires_reason(tmp_path):
    """A justified suppression hides the finding; a bare one (no
    `-- reason`) surfaces as a bare-suppression finding instead."""
    fixtures = os.path.join(
        REPO, "tools", "nomadlint", "fixtures", "donation"
    )
    with open(os.path.join(fixtures, "bad.py")) as fh:
        bad_src = fh.read()
    # findings anchor on the donating CALL line
    justified = bad_src.replace(
        "    out = patch(col, idx, vals)",
        "    # nomadlint: disable=donation-safety -- fixture: "
        "verified safe\n    out = patch(col, idx, vals)",
    )
    assert justified != bad_src
    p1 = tmp_path / "suppressed.py"
    p1.write_text(justified)
    result = run(
        _ctx().with_overrides(scan_files=[str(p1)]),
        ["donation-safety"],
    )
    lines = {f.line for f in result.suppressed}
    assert result.suppressed and lines
    assert all(
        f.rule != "donation-safety" or f.line not in lines
        for f in result.findings
    )

    bare = bad_src.replace(
        "    out = patch(col, idx, vals)",
        "    # nomadlint: disable=donation-safety\n"
        "    out = patch(col, idx, vals)",
    )
    p2 = tmp_path / "bare.py"
    p2.write_text(bare)
    result = run(
        _ctx().with_overrides(scan_files=[str(p2)]),
        ["donation-safety"],
    )
    assert any(
        f.rule == "bare-suppression" for f in result.findings
    ), [f.message for f in result.findings]


def test_wrong_rule_suppression_does_not_hide(tmp_path):
    fixtures = os.path.join(
        REPO, "tools", "nomadlint", "fixtures", "donation"
    )
    with open(os.path.join(fixtures, "bad.py")) as fh:
        bad_src = fh.read()
    wrong = bad_src.replace(
        "    out = patch(col, idx, vals)",
        "    # nomadlint: disable=jit-purity -- wrong rule\n"
        "    out = patch(col, idx, vals)",
    )
    p = tmp_path / "wrong.py"
    p.write_text(wrong)
    result = run(
        _ctx().with_overrides(scan_files=[str(p)]),
        ["donation-safety"],
    )
    assert any(
        f.rule == "donation-safety" for f in result.findings
    )


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.nomadlint", *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )


def test_cli_repo_run_exits_zero_with_json():
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert len(payload["rules_run"]) >= 15


def test_cli_exits_nonzero_on_bad_fixture():
    bad = os.path.join(
        "tools", "nomadlint", "fixtures", "donation", "bad.py"
    )
    proc = _run_cli(
        "--rules", "donation-safety", "--files", bad
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "donation-safety" in proc.stderr


def test_cli_unknown_rule_is_usage_error():
    proc = _run_cli("--rules", "no-such-rule")
    assert proc.returncode == 2


def test_compat_shim_matches_nomadlint():
    """tools/check_stage_accounting.py delegates to the migrated
    rules: its check() agrees with a nomadlint run of the same
    subset."""
    tools_dir = os.path.join(REPO, "tools")
    sys.path.insert(0, tools_dir)
    try:
        import check_stage_accounting as shim
    finally:
        sys.path.remove(tools_dir)
    ok, problems = shim.check()
    assert ok, problems
    result = run(_ctx(), MIGRATED_RULES)
    assert result.ok
    assert len(result.rules_run) == len(MIGRATED_RULES)
