"""State store + columnar node table tests
(reference model: nomad/state/state_store_test.go).
"""
import numpy as np

from nomad_tpu import mock
from nomad_tpu.state import NodeTable, StateStore
from nomad_tpu.structs import (
    NODE_SCHED_INELIGIBLE,
    NODE_STATUS_DOWN,
    PlanResult,
)


def test_upsert_node_indexes():
    s = StateStore()
    n = mock.node()
    idx = s.upsert_node(n)
    assert idx == s.latest_index()
    assert s.node_by_id(n.id) is n
    assert n.computed_class


def test_job_versioning():
    s = StateStore()
    j1 = mock.job(id="j")
    s.upsert_job(j1)
    assert j1.version == 0
    j2 = mock.job(id="j")
    s.upsert_job(j2)
    assert j2.version == 1
    assert s.job_by_version("default", "j", 0) is j1
    assert s.job_by_id("default", "j") is j2


def test_alloc_indexes_and_usage_columns():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    a = mock.alloc(node_id=n.id)
    s.upsert_allocs([a])
    assert s.allocs_by_node(n.id) == [a]
    assert s.allocs_by_job(a.namespace, a.job_id) == [a]
    row = s.node_table.row_of[n.id]
    assert s.node_table.cpu_used[row] == 500
    assert s.node_table.mem_used[row] == 256
    # terminal transition clears usage
    a2 = mock.alloc(id=a.id, node_id=n.id, job_id=a.job_id)
    a2.client_status = "failed"
    s.upsert_allocs([a2])
    assert s.node_table.cpu_used[row] == 0


def test_node_eligibility_column():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    row = s.node_table.row_of[n.id]
    assert s.node_table.eligible[row]
    s.update_node_status(n.id, NODE_STATUS_DOWN)
    assert not s.node_table.eligible[row]


def test_node_drain_toggles_eligibility():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    s.update_node_drain(n.id, True)
    assert s.node_by_id(n.id).scheduling_eligibility == NODE_SCHED_INELIGIBLE
    row = s.node_table.row_of[n.id]
    assert not s.node_table.eligible[row]


def test_plan_results_write_path():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    a = mock.alloc(node_id=n.id)
    result = PlanResult(node_allocation={n.id: [a]})
    s.upsert_plan_results(result)
    assert s.alloc_by_id(a.id) is a


def test_wait_for_index():
    s = StateStore()
    assert s.wait_for_index(0)
    assert not s.wait_for_index(99, timeout=0.05)


def test_node_table_arena_growth_and_reuse():
    t = NodeTable(capacity=2)
    nodes = [mock.node() for _ in range(5)]
    for n in nodes:
        t.upsert_node(n)
    assert t.capacity >= 5
    assert t.active.sum() == 5
    t.delete_node(nodes[0].id)
    assert t.active.sum() == 4
    n_new = mock.node()
    t.upsert_node(n_new)
    # freed row is reused
    assert t.capacity >= 5
    assert t.active.sum() == 5


def test_node_table_column_backfill():
    t = NodeTable()
    a = mock.node()
    a.attributes["zone"] = "z1"
    t.upsert_node(a)
    # column created after the node exists: must backfill
    col = t.column("attr.zone")
    row = t.row_of[a.id]
    assert col.interner.values[col.codes[row]] == "z1"
    b = mock.node()  # no zone attr
    t.upsert_node(b)
    assert col.codes[t.row_of[b.id]] == -1


def test_snapshot_surface():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    snap = s.snapshot()
    assert snap.node_by_id(n.id) is n
    assert len(snap.nodes()) == 1
    assert snap.scheduler_config() is s.scheduler_config


def test_deleted_node_row_reuse_drops_device_reservations():
    """Deleting a node purges its row's device_used entries — a new
    node reusing the freed row must not inherit phantom reservations
    (code-review r4 finding)."""
    from nomad_tpu import mock
    from nomad_tpu.state.store import StateStore
    from nomad_tpu.structs import (
        AllocatedDeviceResource,
        AllocatedResources,
        AllocatedSharedResources,
        AllocatedTaskResources,
    )

    store = StateStore()
    gpu = mock.nvidia_node()
    store.upsert_node(gpu)
    row = store.node_table.row_of[gpu.id]
    alloc = mock.alloc(node_id=gpu.id)
    alloc.allocated_resources = AllocatedResources(
        tasks={
            "t": AllocatedTaskResources(
                cpu=100,
                memory_mb=64,
                devices=[
                    AllocatedDeviceResource(
                        vendor="nvidia",
                        type="gpu",
                        name="1080ti",
                        device_ids=["a", "b"],
                    )
                ],
            )
        },
        shared=AllocatedSharedResources(disk_mb=10),
    )
    store.upsert_allocs([alloc])
    key = (row, ("nvidia", "gpu", "1080ti"))
    assert store.node_table.device_used.get(key) == 2
    store.delete_node(gpu.id)
    assert key not in store.node_table.device_used
    # the freed row gets reused by a fresh GPU node with no
    # reservations
    gpu2 = mock.nvidia_node()
    store.upsert_node(gpu2)
    if store.node_table.row_of[gpu2.id] == row:
        assert key not in store.node_table.device_used


def test_node_table_topo_generation_ignores_no_op_upserts():
    """Heartbeats re-upsert nodes with unchanged state every few
    seconds; those must NOT bump topo_generation (it would thrash
    every topology-keyed cache — candidate/mask/port columns and the
    BatchWorker's device-resident input mirror).  Real changes —
    drain, attribute/fingerprint moves, resource changes — must."""
    from nomad_tpu import mock
    from nomad_tpu.state.store import StateStore

    store = StateStore()
    node = mock.node()
    store.upsert_node(node)
    table = store.node_table
    gen = table.topo_generation

    # no-op re-upsert (heartbeat shape): no topo bump
    store.upsert_node(node)
    assert table.topo_generation == gen

    # status churn that leaves ready() unchanged: no topo bump
    store.update_node_status(node.id, node.status)
    assert table.topo_generation == gen

    # attribute change (driver re-fingerprint): bump
    node.attributes = dict(node.attributes)
    node.attributes["driver.raw_exec"] = "1"
    store.upsert_node(node)
    assert table.topo_generation > gen
    gen = table.topo_generation

    # drain flips eligibility: bump
    store.update_node_drain(node.id, True)
    assert table.topo_generation > gen
    gen = table.topo_generation

    # usage writes never touch topology, only the usage delta log
    ugen = table.usage_generation
    table.update_node_usage(node.id, (100, 200, 300))
    assert table.topo_generation == gen
    assert table.usage_generation == ugen + 1
    row = table.row_of[node.id]
    assert row in table.usage_rows_dirty_since(ugen)
    assert table.usage_rows_dirty_since(table.usage_generation) == []
