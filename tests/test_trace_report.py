"""Smoke tests for tools/trace_report.py — the terminal waterfall
renderer over eval flight-recorder traces (previously the only tool
with zero coverage).  Exercises rendering over a synthetic trace
ring: nesting depth, open spans, bars, attrs, list/summary modes and
the file/stdin loaders."""
import io
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import trace_report  # noqa: E402


def _trace(trace_id="eval-1#1", outcome="prescored"):
    """A synthetic completed trace shaped like /v1/traces/<id>:
    root span, two children (one nested two deep), one open span."""
    return {
        "trace_id": trace_id,
        "outcome": outcome,
        "duration_ms": 12.5,
        "dropped": 0,
        "attrs": {"queue": "service"},
        "spans": [
            {
                "id": 1, "parent": None,
                "name": "broker.dequeue",
                "off_ms": 0.0, "dur_ms": 0.05,
                "attrs": {"queue": "service"},
            },
            {
                "id": 2, "parent": 1,
                "name": "batch_worker.simulate",
                "off_ms": 0.2, "dur_ms": 6.0,
                "thread": "worker-0",
            },
            {
                "id": 3, "parent": 2,
                "name": "batch_worker.launch",
                "off_ms": 1.0, "dur_ms": 4.0,
            },
            {
                "id": 4, "parent": 1,
                "name": "batch_worker.replay",
                "off_ms": 7.0, "dur_ms": None,  # still open
            },
        ],
    }


def test_render_trace_waterfall_shape():
    text = trace_report.render_trace(_trace())
    lines = text.splitlines()
    # header: id, outcome, duration, span count
    assert "trace eval-1#1" in lines[0]
    assert "outcome=prescored" in lines[0]
    assert "12.50ms" in lines[0]
    assert "spans=4" in lines[0]
    # trace attrs on the second header line
    assert "queue=service" in lines[1]
    body = "\n".join(lines[2:])
    assert "broker.dequeue" in body
    assert "batch_worker.simulate" in body
    # depth indentation: the nested launch span is indented two
    # levels (its parent simulate is one level under the root)
    launch_row = next(
        ln for ln in lines if "batch_worker.launch" in ln
    )
    assert "    batch_worker.launch" in launch_row
    # open span renders OPEN instead of a duration
    replay_row = next(
        ln for ln in lines if "batch_worker.replay" in ln
    )
    assert "OPEN" in replay_row
    # proportional bars appear for measured spans
    assert "=" * 4 in body
    # per-span thread attribution surfaces
    assert "thread=worker-0" in body


def test_render_trace_in_flight_header():
    trace = _trace()
    trace["duration_ms"] = None
    text = trace_report.render_trace(trace)
    assert "(in flight)" in text.splitlines()[0]


def test_render_orphans_and_drops_flagged():
    trace = _trace()
    trace["dropped"] = 3
    trace["orphans"] = 2
    header = trace_report.render_trace(trace).splitlines()[0]
    assert "dropped=3" in header
    assert "ORPHANS=2" in header


def test_render_list_full_and_summary_modes():
    full = _trace("eval-a#1")
    summary = {
        "trace_id": "eval-b#1",
        "outcome": "sequential",
        "duration_ms": 3.25,
        "spans": 7,
    }
    text = trace_report.render([full, summary])
    parts = text.split("\n\n")
    assert len(parts) == 2
    assert "broker.dequeue" in parts[0]
    # summaries point at the per-eval endpoint for the waterfall
    assert "eval-b#1" in parts[1]
    assert "fetch /v1/traces/<eval_id>" in parts[1]
    assert "spans=7" in parts[1]


def test_render_empty_spans_ring():
    """A trace whose ring overflowed to nothing still renders a
    header (no div-by-zero on the bar scale, no max() on empty)."""
    text = trace_report.render_trace(
        {
            "trace_id": "eval-empty#1",
            "outcome": "prescored",
            "duration_ms": 0.0,
            "spans": [],
        }
    )
    assert "spans=0" in text


def test_load_from_file_and_stdin(tmp_path, monkeypatch):
    payload = _trace("eval-file#1")
    p = tmp_path / "trace.json"
    p.write_text(json.dumps(payload))
    assert trace_report._load(str(p)) == payload
    monkeypatch.setattr(
        sys, "stdin", io.StringIO(json.dumps(payload))
    )
    assert trace_report._load("-") == payload


def test_main_renders_file(tmp_path, capsys):
    p = tmp_path / "ring.json"
    p.write_text(json.dumps([_trace("eval-ring#1")]))
    rc = trace_report.main(["trace_report.py", str(p)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "trace eval-ring#1" in out
    assert "batch_worker.simulate" in out


def test_main_usage_error(capsys):
    assert trace_report.main(["trace_report.py"]) == 2
    assert (
        trace_report.main(["trace_report.py", "--help"]) == 2
    )
    err = capsys.readouterr().err
    assert "waterfall" in err
