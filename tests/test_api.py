"""HTTP API, jobspec and CLI tests (reference model:
command/agent/http_test.go, jobspec/parse_test.go).
"""
import io
import json
import time
import urllib.request
from contextlib import redirect_stdout

import pytest

from nomad_tpu import jobspec, mock
from nomad_tpu.api import start_http_server
from nomad_tpu.api.codec import job_from_dict, job_to_dict
from nomad_tpu.server import Server


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


HCL_JOB = """
# a comment
job "web-app" {
  datacenters = ["dc1", "dc2"]
  type        = "service"
  priority    = 70

  constraint {
    attribute = "${attr.kernel.name}"
    value     = "linux"
  }

  update {
    max_parallel = 2
    canary       = 1
    auto_revert  = true
    min_healthy_time = "5s"
  }

  group "frontend" {
    count = 3

    spread {
      attribute = "${node.datacenter}"
      weight    = 60
      target "dc1" { percent = 70 }
      target "dc2" { percent = 30 }
    }

    restart {
      attempts = 2
      interval = "30m"
      delay    = "15s"
      mode     = "fail"
    }

    ephemeral_disk { size = 500 }

    task "server" {
      driver = "mock_driver"
      config {
        run_for = -1
      }
      env {
        PORT = "8080"
      }
      resources {
        cpu    = 500
        memory = 256
      }
    }
  }
}
"""


def test_jobspec_parse():
    job = jobspec.parse(HCL_JOB)
    assert job.id == "web-app"
    assert job.type == "service"
    assert job.priority == 70
    assert job.datacenters == ["dc1", "dc2"]
    assert len(job.constraints) == 1
    assert job.constraints[0].ltarget == "${attr.kernel.name}"
    assert job.update is not None and job.update.canary == 1
    assert job.update.min_healthy_time_s == 5.0
    tg = job.task_groups[0]
    assert tg.name == "frontend" and tg.count == 3
    assert tg.spreads[0].attribute == "${node.datacenter}"
    assert tg.spreads[0].targets[0].value == "dc1"
    assert tg.spreads[0].targets[0].percent == 70
    assert tg.restart_policy.interval_s == 1800.0
    assert tg.ephemeral_disk.size_mb == 500
    # job-level update propagates to groups
    assert tg.update is not None
    task = tg.tasks[0]
    assert task.driver == "mock_driver"
    assert task.config == {"run_for": -1}
    assert task.env == {"PORT": "8080"}
    assert task.resources.cpu == 500
    assert task.resources.memory_mb == 256


def test_job_json_roundtrip():
    job = jobspec.parse(HCL_JOB)
    d = job_to_dict(job)
    restored = job_from_dict(json.loads(json.dumps(d)))
    assert restored.id == job.id
    assert restored.task_groups[0].count == 3
    assert restored.task_groups[0].tasks[0].resources.cpu == 500
    assert restored.update.canary == 1


@pytest.fixture
def api():
    # two schedulers + a short nack timeout: the broker's at-least-once
    # redelivery and worker redundancy absorb a stuck worker thread
    # (this sandbox's scheduler has been observed to freeze a newly
    # created thread indefinitely — see eval_broker ticker note)
    server = Server(
        num_schedulers=2, heartbeat_ttl=60.0, seed=33, nack_timeout=5.0
    )
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    yield server, base
    http.stop()
    server.stop()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def _post(base, path, body, method="POST"):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def test_http_job_lifecycle(api):
    server, base = api
    for _ in range(3):
        server.register_node(mock.node())

    job = jobspec.parse(HCL_JOB)
    job.task_groups[0].update = None
    job.update = None
    resp = _post(base, "/v1/jobs", {"Job": job_to_dict(job)})
    assert resp["EvalID"]

    jobs = _get(base, "/v1/jobs")
    assert [j["ID"] for j in jobs] == ["web-app"]

    detail = _get(base, "/v1/job/web-app")
    assert detail["priority"] == 70

    assert wait_until(
        lambda: len(_get(base, "/v1/job/web-app/allocations")) == 3,
        timeout=30,
    ), (
        f"evals={[(e.status, e.triggered_by) for e in server.store.evals_by_job('default', 'web-app')]} "
        f"broker={server.broker.stats} events={list(server.broker.events)}"
    )
    allocs = _get(base, "/v1/job/web-app/allocations")

    evals = _get(base, "/v1/job/web-app/evaluations")
    assert evals and evals[0]["status"] == "complete"

    alloc = _get(base, f"/v1/allocation/{allocs[0]['id']}")
    assert alloc["job_id"] == "web-app"

    # scale up
    resp = _post(
        base, "/v1/job/web-app/scale",
        {"Target": {"Group": "frontend"}, "Count": 5},
    )
    assert server.drain_to_idle(10)
    assert wait_until(
        lambda: len(
            [
                a
                for a in server.store.allocs_by_job("default", "web-app")
                if not a.terminal_status()
            ]
        )
        == 5
    )

    # stop
    _post(base, "/v1/job/web-app", {}, method="DELETE")
    assert server.drain_to_idle(10)
    assert wait_until(
        lambda: not [
            a
            for a in server.store.allocs_by_job("default", "web-app")
            if a.desired_status == "run"
        ]
    )


def test_http_nodes_and_search(api):
    server, base = api
    n = mock.node()
    server.register_node(n)
    nodes = _get(base, "/v1/nodes")
    assert nodes[0]["ID"] == n.id
    detail = _get(base, f"/v1/node/{n.id}")
    assert detail["datacenter"] == "dc1"

    # drain via API
    _post(base, f"/v1/node/{n.id}/drain",
          {"DrainSpec": {"Deadline": int(60e9)}})
    assert server.store.node_by_id(n.id).drain

    # search
    result = _post(
        base, "/v1/search", {"Prefix": n.id[:4], "Context": "nodes"}
    )
    assert n.id in result["Matches"]["nodes"]


def test_http_operator_scheduler_config(api):
    server, base = api
    cfg = _get(base, "/v1/operator/scheduler/configuration")
    assert cfg["SchedulerAlgorithm"] == "binpack"
    assert cfg["TPUSchedulerEnabled"] is False
    cfg["TPUSchedulerEnabled"] = True
    cfg["SchedulerAlgorithm"] = "spread"
    _post(base, "/v1/operator/scheduler/configuration", cfg)
    assert server.store.get_scheduler_config().tpu_scheduler_enabled
    assert (
        server.store.get_scheduler_config().scheduler_algorithm
        == "spread"
    )


def test_http_404s(api):
    _server, base = api
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(base, "/v1/job/nope")
    assert exc.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(base, "/v1/bogus")
    assert exc.value.code == 404


def test_cli_against_live_agent(api, monkeypatch, tmp_path):
    server, base = api
    from nomad_tpu import cli

    monkeypatch.setenv("NOMAD_ADDR", base)
    server.register_node(mock.node())

    spec = tmp_path / "job.hcl"
    spec.write_text(HCL_JOB.replace('canary       = 1', 'canary = 0'))

    out = io.StringIO()
    with redirect_stdout(out):
        cli.main(["job", "run", str(spec)])
    assert "Evaluation" in out.getvalue()
    assert server.drain_to_idle(10)

    out = io.StringIO()
    with redirect_stdout(out):
        cli.main(["job", "status"])
    assert "web-app" in out.getvalue()

    out = io.StringIO()
    with redirect_stdout(out):
        cli.main(["job", "status", "web-app"])
    assert "Allocations" in out.getvalue()

    out = io.StringIO()
    with redirect_stdout(out):
        cli.main(["node", "status"])
    assert "dc1" in out.getvalue()

    out = io.StringIO()
    with redirect_stdout(out):
        cli.main(["version"])
    assert "nomad-tpu" in out.getvalue()
