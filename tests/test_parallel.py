"""Multi-chip sharding tests on the virtual 8-device CPU mesh: sharded
kernels must agree with the single-device kernels exactly.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nomad_tpu.ops.batch import BatchInputs, batch_plan_picks, plan_picks
from nomad_tpu.ops.score import ScoreInputs, score_and_select
from nomad_tpu.parallel import (
    make_mesh,
    sharded_batch_plan,
    sharded_score_and_select,
)


C = 256  # arena capacity, divisible by the node axis


def _random_inputs(rng, n_active=200):
    cpu_total = np.zeros(C)
    mem_total = np.zeros(C)
    disk_total = np.zeros(C)
    cpu_total[:n_active] = rng.choice([2000, 4000, 8000], n_active)
    mem_total[:n_active] = rng.choice([4096, 8192], n_active)
    disk_total[:n_active] = 100_000.0
    cpu_used = np.zeros(C)
    mem_used = np.zeros(C)
    cpu_used[:n_active] = rng.integers(0, 1500, n_active)
    mem_used[:n_active] = rng.integers(0, 2000, n_active)
    feasible = np.zeros(C, dtype=bool)
    feasible[:n_active] = rng.random(n_active) > 0.1
    perm = np.concatenate(
        [rng.permutation(n_active), np.arange(n_active, C)]
    ).astype(np.int32)
    return ScoreInputs(
        cpu_total=cpu_total,
        mem_total=mem_total,
        disk_total=disk_total,
        cpu_used=cpu_used,
        mem_used=mem_used,
        disk_used=np.zeros(C),
        feasible=feasible,
        collisions=rng.integers(0, 3, C).astype(np.int32),
        penalty=rng.random(C) > 0.9,
        affinity_score=np.zeros(C),
        spread_boost=np.zeros(C),
        perm=perm,
        ask_cpu=np.float64(500),
        ask_mem=np.float64(256),
        ask_disk=np.float64(300),
        desired_count=np.int32(10),
        limit=np.int32(8),
        n_candidates=np.int32(n_active),
    )


def test_mesh_axes():
    mesh = make_mesh(8, backend="cpu")
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("evals", "nodes")


@pytest.mark.parametrize("seed", range(4))
def test_sharded_select_matches_single_device(seed):
    rng = np.random.default_rng(seed)
    inp = _random_inputs(rng)
    with jax.default_device(jax.devices("cpu")[0]):
        row1, score1, n1, pulls1 = jax.tree.map(
            np.asarray, score_and_select(inp)
        )
    mesh = make_mesh(8, backend="cpu")
    sharded = sharded_score_and_select(mesh)
    row2, score2, n2, pulls2 = jax.tree.map(np.asarray, sharded(inp))
    assert int(row1) == int(row2)
    assert float(score1) == float(score2)
    assert int(n1) == int(n2)
    assert int(pulls1) == int(pulls2)


def _batch_inputs(rng, E, n_active=200):
    def one():
        feas = np.zeros(C, dtype=bool)
        feas[:n_active] = True
        cpu_used = np.zeros(C)
        mem_used = np.zeros(C)
        cpu_used[:n_active] = rng.integers(0, 1000, n_active)
        mem_used[:n_active] = rng.integers(0, 1000, n_active)
        perm = np.concatenate(
            [rng.permutation(n_active), np.arange(n_active, C)]
        ).astype(np.int32)
        return BatchInputs(
            feasible=feas,
            base_cpu_used=cpu_used,
            base_mem_used=mem_used,
            base_disk_used=np.zeros(C),
            base_collisions=np.zeros(C, dtype=np.int32),
            penalty=np.zeros(C, dtype=bool),
            affinity_score=np.zeros(C),
            perm=perm,
            ask_cpu=np.float64(500),
            ask_mem=np.float64(256),
            ask_disk=np.float64(300),
            desired_count=np.int32(5),
            limit=np.int32(8),
            distinct_hosts=np.bool_(False),
        )

    evals = [one() for _ in range(E)]
    return BatchInputs(
        *[np.stack([getattr(e, f) for e in evals]) for f in BatchInputs._fields]
    )


def test_batch_scan_plan_updates_state_between_picks():
    rng = np.random.default_rng(0)
    batch = _batch_inputs(rng, E=1)
    single = jax.tree.map(lambda x: x[0], batch)
    cpu_total = np.full(C, 4000.0)
    mem_total = np.full(C, 8192.0)
    disk_total = np.full(C, 100_000.0)
    with jax.default_device(jax.devices("cpu")[0]):
        rows = np.asarray(
            plan_picks(
                cpu_total, mem_total, disk_total, single,
                np.int32(200), 5,
            )
        )
    assert (rows >= 0).all()
    # anti-affinity must spread the 5 picks over 5 distinct nodes
    assert len(set(rows.tolist())) == 5


def test_sharded_batch_matches_single_device():
    rng = np.random.default_rng(1)
    E, P_ = 4, 3
    batch = _batch_inputs(rng, E=E)
    cpu_total = np.full(C, 4000.0)
    mem_total = np.full(C, 8192.0)
    disk_total = np.full(C, 100_000.0)
    with jax.default_device(jax.devices("cpu")[0]):
        rows1 = np.asarray(
            batch_plan_picks(
                cpu_total, mem_total, disk_total, batch,
                np.int32(200), P_,
            )
        )
    mesh = make_mesh(8, backend="cpu")
    run = sharded_batch_plan(mesh, n_candidates=200, n_picks=P_)
    rows2 = np.asarray(run(cpu_total, mem_total, disk_total, batch))
    assert (rows1 == rows2).all()
