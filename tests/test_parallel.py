"""Multi-chip sharding tests on the virtual 8-device CPU mesh: sharded
kernels must agree with the single-device kernels exactly.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nomad_tpu.ops.batch import BatchInputs, batch_plan_picks, plan_picks
from nomad_tpu.ops.score import ScoreInputs, score_and_select
from nomad_tpu.parallel import (
    make_mesh,
    sharded_batch_plan,
    sharded_score_and_select,
)


C = 256  # arena capacity, divisible by the node axis


def _random_inputs(rng, n_active=200):
    cpu_total = np.zeros(C)
    mem_total = np.zeros(C)
    disk_total = np.zeros(C)
    cpu_total[:n_active] = rng.choice([2000, 4000, 8000], n_active)
    mem_total[:n_active] = rng.choice([4096, 8192], n_active)
    disk_total[:n_active] = 100_000.0
    cpu_used = np.zeros(C)
    mem_used = np.zeros(C)
    cpu_used[:n_active] = rng.integers(0, 1500, n_active)
    mem_used[:n_active] = rng.integers(0, 2000, n_active)
    feasible = np.zeros(C, dtype=bool)
    feasible[:n_active] = rng.random(n_active) > 0.1
    perm = np.concatenate(
        [rng.permutation(n_active), np.arange(n_active, C)]
    ).astype(np.int32)
    return ScoreInputs(
        cpu_total=cpu_total,
        mem_total=mem_total,
        disk_total=disk_total,
        cpu_used=cpu_used,
        mem_used=mem_used,
        disk_used=np.zeros(C),
        feasible=feasible,
        collisions=rng.integers(0, 3, C).astype(np.int32),
        penalty=rng.random(C) > 0.9,
        affinity_score=np.zeros(C),
        spread_boost=np.zeros(C),
        perm=perm,
        ask_cpu=np.float64(500),
        ask_mem=np.float64(256),
        ask_disk=np.float64(300),
        desired_count=np.int32(10),
        limit=np.int32(8),
        n_candidates=np.int32(n_active),
    )


def test_mesh_axes():
    mesh = make_mesh(8, backend="cpu")
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("evals", "nodes")


@pytest.mark.parametrize("seed", range(4))
def test_sharded_select_matches_single_device(seed):
    rng = np.random.default_rng(seed)
    inp = _random_inputs(rng)
    with jax.default_device(jax.devices("cpu")[0]):
        row1, score1, n1, pulls1 = jax.tree.map(
            np.asarray, score_and_select(inp)
        )
    mesh = make_mesh(8, backend="cpu")
    sharded = sharded_score_and_select(mesh)
    row2, score2, n2, pulls2 = jax.tree.map(np.asarray, sharded(inp))
    assert int(row1) == int(row2)
    assert float(score1) == float(score2)
    assert int(n1) == int(n2)
    assert int(pulls1) == int(pulls2)


def _batch_inputs(rng, E, n_active=200):
    def one():
        feas = np.zeros(C, dtype=bool)
        feas[:n_active] = True
        cpu_used = np.zeros(C)
        mem_used = np.zeros(C)
        cpu_used[:n_active] = rng.integers(0, 1000, n_active)
        mem_used[:n_active] = rng.integers(0, 1000, n_active)
        perm = np.concatenate(
            [rng.permutation(n_active), np.arange(n_active, C)]
        ).astype(np.int32)
        return BatchInputs(
            feasible=feas,
            base_cpu_used=cpu_used,
            base_mem_used=mem_used,
            base_disk_used=np.zeros(C),
            base_collisions=np.zeros(C, dtype=np.int32),
            penalty=np.zeros(C, dtype=bool),
            affinity_score=np.zeros(C),
            perm=perm,
            ask_cpu=np.float64(500),
            ask_mem=np.float64(256),
            ask_disk=np.float64(300),
            desired_count=np.int32(5),
            limit=np.int32(8),
            distinct_hosts=np.bool_(False),
        )

    evals = [one() for _ in range(E)]
    return BatchInputs(
        *[np.stack([getattr(e, f) for e in evals]) for f in BatchInputs._fields]
    )


def test_batch_scan_plan_updates_state_between_picks():
    rng = np.random.default_rng(0)
    batch = _batch_inputs(rng, E=1)
    single = jax.tree.map(lambda x: x[0], batch)
    cpu_total = np.full(C, 4000.0)
    mem_total = np.full(C, 8192.0)
    disk_total = np.full(C, 100_000.0)
    with jax.default_device(jax.devices("cpu")[0]):
        rows = np.asarray(
            plan_picks(
                cpu_total, mem_total, disk_total, single,
                np.int32(200), 5,
            )
        )
    assert (rows >= 0).all()
    # anti-affinity must spread the 5 picks over 5 distinct nodes
    assert len(set(rows.tolist())) == 5


def test_sharded_batch_matches_single_device():
    rng = np.random.default_rng(1)
    E, P_ = 4, 3
    batch = _batch_inputs(rng, E=E)
    cpu_total = np.full(C, 4000.0)
    mem_total = np.full(C, 8192.0)
    disk_total = np.full(C, 100_000.0)
    with jax.default_device(jax.devices("cpu")[0]):
        rows1 = np.asarray(
            batch_plan_picks(
                cpu_total, mem_total, disk_total, batch,
                np.int32(200), P_,
            )
        )
    mesh = make_mesh(8, backend="cpu")
    run = sharded_batch_plan(mesh, n_candidates=200, n_picks=P_)
    rows2 = np.asarray(run(cpu_total, mem_total, disk_total, batch))
    assert (rows1 == rows2).all()


def test_sharded_chained_plan_matches_unsharded():
    """sharded_chained_plan (node-axis sharded production launch) must
    produce bit-identical rows to chained_plan_picks_cols for the same
    inputs, including steady-state deltas, pre-placement rows,
    distinct_hosts, affinities and failure coalescing."""
    import numpy as np

    from nomad_tpu.ops.batch import (
        ChainInputs,
        PreDeltas,
        StepDeltas,
        chained_plan_picks_cols,
    )
    from nomad_tpu.parallel import make_mesh
    from nomad_tpu.parallel.mesh import sharded_chained_plan

    rng = np.random.default_rng(17)
    C, E, P, K, R = 128, 4, 8, 4, 2
    cpu_total = rng.choice([4000.0, 8000.0], C)
    mem_total = rng.choice([8192.0, 16384.0], C)
    disk_total = np.full(C, 100_000.0)
    used_cpu = rng.integers(0, 2000, C).astype(np.float64)
    used_mem = rng.integers(0, 4096, C).astype(np.float64)
    used_disk = np.zeros(C)

    n_cand = 120
    feasible = np.zeros((E, C), dtype=bool)
    perms = np.zeros((E, C), np.int32)
    for e in range(E):
        feasible[e, :n_cand] = rng.random(n_cand) > 0.1
        perms[e] = np.concatenate(
            [rng.permutation(n_cand), np.arange(n_cand, C)]
        )
    coll0 = (rng.random((E, C)) > 0.9).astype(np.int32)
    affinity = np.where(rng.random((E, C)) > 0.8, 0.35, 0.0)
    deltas = StepDeltas(
        evict_rows=np.where(
            rng.random((E, P)) > 0.7,
            rng.integers(0, n_cand, (E, P)),
            -1,
        ).astype(np.int32),
        evict_cpu=np.full((E, P), -500.0),
        evict_mem=np.full((E, P), -256.0),
        evict_disk=np.zeros((E, P)),
        evict_coll=np.zeros((E, P), np.int32),
        penalty_rows=np.where(
            rng.random((E, P, K)) > 0.8,
            rng.integers(0, n_cand, (E, P, K)),
            -1,
        ).astype(np.int32),
    )
    pre = PreDeltas(
        rows=rng.integers(0, n_cand, (E, R)).astype(np.int32),
        cpu=np.full((E, R), -100.0),
        mem=np.full((E, R), -128.0),
        disk=np.zeros((E, R)),
    )
    asks = (
        np.full(E, 500.0),
        np.full(E, 256.0),
        np.full(E, 300.0),
    )
    desired = np.full(E, 5, np.int32)
    limits = np.full(E, 7, np.int32)
    wanted = np.asarray([5, 3, 5, 0], np.int32)
    ncands = np.full(E, n_cand, np.int32)
    dh = np.asarray([False, True, False, False])

    # the cols kernel takes the group-routed layout (T=1, per-pick
    # scalars broadcast); the sharded runner keeps per-eval scalars
    stacked = ChainInputs(
        feasible=feasible[:, None],
        perm=perms,
        ask_cpu=np.tile(asks[0][:, None], (1, P)),
        ask_mem=np.tile(asks[1][:, None], (1, P)),
        ask_disk=np.tile(asks[2][:, None], (1, P)),
        desired_count=np.tile(desired[:, None], (1, P)),
        limit=np.tile(limits[:, None], (1, P)),
        distinct_hosts=dh,
        tg_idx=np.zeros((E, P), np.int32),
    )
    ref_rows, ref_pulls = chained_plan_picks_cols(
        cpu_total, mem_total, disk_total,
        used_cpu, used_mem, used_disk,
        stacked, ncands, P,
        wanted=wanted, coll0=coll0[:, None],
        affinity=affinity[:, None],
        deltas=deltas, pre=pre,
    )
    ref_rows = np.asarray(ref_rows)
    mesh = make_mesh(8, eval_axis=1)
    run = sharded_chained_plan(mesh, P)
    got_rows, got_pulls = run(
        cpu_total, mem_total, disk_total,
        used_cpu, used_mem, used_disk,
        feasible, perms, *asks, desired, limits, wanted,
        ncands, dh, coll0, affinity, deltas, pre,
    )
    got_rows = np.asarray(got_rows)
    assert np.array_equal(ref_rows, got_rows), (ref_rows, got_rows)
    # the surfaced pulls must match too: mesh-path preempt retries
    # seed the sequential passthrough from them
    assert np.array_equal(
        np.asarray(ref_pulls), np.asarray(got_pulls)
    )


def test_sharded_chained_plan_flops_scale_with_devices():
    """Per-device FLOPs of the sharded launch must scale ~1/devices
    (the VERDICT r2 item 6 acceptance: scoring work is node-sharded,
    only the walk over the gathered score vector is replicated)."""
    import numpy as np

    from nomad_tpu.ops.batch import PreDeltas, StepDeltas
    from nomad_tpu.parallel import make_mesh
    from nomad_tpu.parallel.mesh import sharded_chained_plan

    C, E, P, K, R = 1024, 2, 4, 2, 1
    n_cand = C - 8

    def build_args():
        rng = np.random.default_rng(3)
        perms = np.stack(
            [
                np.concatenate(
                    [rng.permutation(n_cand), np.arange(n_cand, C)]
                )
                for _ in range(E)
            ]
        ).astype(np.int32)
        feas = np.ones((E, C), dtype=bool)
        return (
            np.full(C, 8000.0), np.full(C, 16384.0),
            np.full(C, 100_000.0),
            np.zeros(C), np.zeros(C), np.zeros(C),
            feas, perms,
            np.full(E, 500.0), np.full(E, 256.0), np.full(E, 300.0),
            np.full(E, P, np.int32), np.full(E, 9, np.int32),
            np.full(E, P, np.int32), np.full(E, n_cand, np.int32),
            np.zeros(E, dtype=bool),
            np.zeros((E, C), np.int32), np.zeros((E, C)),
            StepDeltas(
                evict_rows=np.full((E, P), -1, np.int32),
                evict_cpu=np.zeros((E, P)),
                evict_mem=np.zeros((E, P)),
                evict_disk=np.zeros((E, P)),
                evict_coll=np.zeros((E, P), np.int32),
                penalty_rows=np.full((E, P, K), -1, np.int32),
            ),
            PreDeltas(
                rows=np.zeros((E, R), np.int32),
                cpu=np.zeros((E, R)), mem=np.zeros((E, R)),
                disk=np.zeros((E, R)),
            ),
        )

    def flops(n_dev):
        mesh = make_mesh(n_dev, eval_axis=1)
        run = sharded_chained_plan(mesh, P)
        # run.__wrapped__ is the jitted fn; lower+compile for analysis
        lowered = run.lower(*build_args())
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0))

    f1 = flops(1)
    f8 = flops(8)
    # cost_analysis reports per-device flops for SPMD programs; the
    # node-sharded scoring should shrink ~8x, with the replicated walk
    # keeping a floor — require at least 3x
    assert f8 > 0 and f1 > 0
    assert f1 / f8 >= 3.0, f"flops did not scale: f1={f1} f8={f8}"


def test_batch_worker_sharded_prescore_matches_sequential(monkeypatch):
    """With NOMAD_TPU_MESH=1 the BatchWorker shards its chained
    prescore launches over the 8-device node mesh; placements must stay
    bit-identical to the sequential scheduler."""
    import copy
    import random as _random

    from nomad_tpu import mock
    from nomad_tpu.server import Server
    from nomad_tpu.structs import compute_node_class

    monkeypatch.setenv("NOMAD_TPU_MESH", "1")

    rng = _random.Random(71)
    nodes = []
    for _ in range(24):
        node = mock.node()
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    # 12 jobs: bursts bigger than one PIPELINE_CHUNK exercise the
    # mesh path's eval-axis re-padding (chunk-aligned arena -> the
    # historical {8, BATCH_MAX} sharded buckets)
    jobs = []
    for i in range(12):
        job = mock.job(id=f"mesh-{i}")
        job.task_groups[0].count = rng.randint(1, 5)
        job.task_groups[0].tasks[0].resources.cpu = rng.choice(
            [200, 500]
        )
        jobs.append(job)

    seq = Server(num_schedulers=1, seed=83, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=83, batch_pipeline=True)
    assert bat.workers[0]._mesh is not None
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(20)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)

        def placements(server, job_id):
            return sorted(
                (a.name, a.node_id)
                for a in server.store.allocs_by_job("default", job_id)
                if not a.terminal_status()
            )

        for job in jobs:
            assert placements(seq, job.id) == placements(
                bat, job.id
            ), f"mesh divergence for {job.id}"
        assert bat.workers[0].prescored > 0
    finally:
        seq.stop()
        bat.stop()


def test_sharded_chained_plan_spread_matches_unsharded():
    """with_spread=True: the sharded planner's spread carry (percent
    AND even mode, incl. the PopulateProposed cleared-decrement quirk
    and per-pick evictee slot clearing) must match the unsharded
    kernel bit for bit."""
    import numpy as np

    from nomad_tpu.ops.batch import (
        ChainInputs,
        PreDeltas,
        SpreadInputs,
        StepDeltas,
        chained_plan_picks_cols,
    )
    from nomad_tpu.parallel import make_mesh
    from nomad_tpu.parallel.mesh import sharded_chained_plan

    rng = np.random.default_rng(29)
    C, E, P, K, R, S, V1 = 64, 3, 6, 4, 2, 2, 4
    cpu_total = rng.choice([4000.0, 8000.0], C)
    mem_total = rng.choice([8192.0, 16384.0], C)
    disk_total = np.full(C, 100_000.0)
    used_cpu = rng.integers(0, 2000, C).astype(np.float64)
    used_mem = rng.integers(0, 4096, C).astype(np.float64)
    used_disk = np.zeros(C)

    n_cand = 60
    feasible = np.zeros((E, C), dtype=bool)
    perms = np.zeros((E, C), np.int32)
    for e in range(E):
        feasible[e, :n_cand] = rng.random(n_cand) > 0.1
        perms[e] = np.concatenate(
            [rng.permutation(n_cand), np.arange(n_cand, C)]
        )
    deltas = StepDeltas(
        evict_rows=np.where(
            rng.random((E, P)) > 0.6,
            rng.integers(0, n_cand, (E, P)),
            -1,
        ).astype(np.int32),
        evict_cpu=np.full((E, P), -400.0),
        evict_mem=np.full((E, P), -128.0),
        evict_disk=np.zeros((E, P)),
        evict_coll=np.zeros((E, P), np.int32),
        penalty_rows=np.full((E, P, K), -1, np.int32),
    )
    pre = PreDeltas(
        rows=np.zeros((E, R), np.int32),
        cpu=np.zeros((E, R)),
        mem=np.zeros((E, R)),
        disk=np.zeros((E, R)),
    )
    # spread stanzas: stanza 0 percent-target, stanza 1 even-mode
    codes = rng.integers(0, V1, (E, S, C)).astype(np.int32)
    desired = rng.integers(1, 5, (E, S, V1)).astype(np.float64)
    used0 = rng.integers(0, 3, (E, S, V1)).astype(np.float64)
    prop0 = rng.integers(0, 2, (E, S, V1)).astype(np.float64)
    cleared0 = rng.integers(0, 2, (E, S, V1)).astype(np.float64)
    weight = np.full((E, S), 0.5)
    active = np.ones((E, S), dtype=bool)
    even = np.zeros((E, S), dtype=bool)
    even[:, 1] = True
    spread = SpreadInputs(
        codes=codes, desired=desired, used0=used0,
        proposed0=prop0, cleared0=cleared0, weight=weight,
        active=active, even=even,
    )

    asks = (
        np.full(E, 300.0), np.full(E, 256.0), np.full(E, 300.0)
    )
    desired_count = np.full(E, 4, np.int32)
    limits = np.full(E, 2**31 - 1, np.int32)  # spreads lift the limit
    wanted = np.full(E, P, np.int32)
    ncands = np.full(E, n_cand, np.int32)
    dh = np.zeros(E, bool)
    coll0 = np.zeros((E, C), np.int32)
    affinity = np.zeros((E, C))

    stacked = ChainInputs(
        feasible=feasible[:, None],
        perm=perms,
        ask_cpu=np.tile(asks[0][:, None], (1, P)),
        ask_mem=np.tile(asks[1][:, None], (1, P)),
        ask_disk=np.tile(asks[2][:, None], (1, P)),
        desired_count=np.tile(desired_count[:, None], (1, P)),
        limit=np.tile(limits[:, None], (1, P)),
        distinct_hosts=dh,
        tg_idx=np.zeros((E, P), np.int32),
    )
    ref = np.asarray(
        chained_plan_picks_cols(
            cpu_total, mem_total, disk_total,
            used_cpu, used_mem, used_disk,
            stacked, ncands, P,
            wanted=wanted, deltas=deltas, pre=pre,
            spread=spread,
        )[0]
    )
    mesh = make_mesh(8, eval_axis=1)
    run = sharded_chained_plan(mesh, P, with_spread=True)
    got, _pulls = run(
        cpu_total, mem_total, disk_total,
        used_cpu, used_mem, used_disk,
        feasible, perms, *asks, desired_count, limits, wanted,
        ncands, dh, coll0, affinity, deltas, pre, spread,
    )
    got = np.asarray(got)
    assert np.array_equal(ref, got), (ref, got)


def test_batch_worker_mesh_used_under_spread(monkeypatch):
    """Config-3-style stream: spread jobs must exercise the sharded
    multi-chip path (mesh_used > 0), with placements bit-identical to
    the sequential scheduler (VERDICT r4 #9)."""
    import copy
    import random as _random

    from nomad_tpu import mock
    from nomad_tpu.server import Server
    from nomad_tpu.structs import Spread, SpreadTarget, compute_node_class

    monkeypatch.setenv("NOMAD_TPU_MESH", "1")

    rng = _random.Random(13)
    nodes = []
    for i in range(24):
        node = mock.node()
        node.datacenter = ["dc1", "dc2", "dc3"][i % 3]
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    jobs = []
    for i in range(4):
        job = mock.job(id=f"spread-mesh-{i}")
        job.datacenters = ["dc1", "dc2", "dc3"]
        job.task_groups[0].count = 4
        job.task_groups[0].tasks[0].resources.cpu = 200
        if i % 2 == 0:
            # percent-target spread
            job.spreads = [
                Spread(
                    attribute="${node.datacenter}",
                    weight=50,
                    targets=[
                        SpreadTarget(value="dc1", percent=50),
                        SpreadTarget(value="dc2", percent=30),
                        SpreadTarget(value="dc3", percent=20),
                    ],
                )
            ]
        else:
            # even-mode spread (no targets)
            job.spreads = [
                Spread(
                    attribute="${node.datacenter}", weight=50
                )
            ]
        jobs.append(job)

    seq = Server(num_schedulers=1, seed=37, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=37, batch_pipeline=True)
    assert bat.workers[0]._mesh is not None
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(20)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)

        def placements(server, job_id):
            return sorted(
                (a.name, a.node_id)
                for a in server.store.allocs_by_job("default", job_id)
                if not a.terminal_status()
            )

        for job in jobs:
            assert placements(seq, job.id) == placements(
                bat, job.id
            ), f"mesh spread divergence for {job.id}"
        worker = bat.workers[0]
        assert worker.mesh_used > 0, (
            worker.mesh_used, worker.prescored, worker.fallbacks,
        )
        assert worker.prescored > 0
    finally:
        seq.stop()
        bat.stop()
