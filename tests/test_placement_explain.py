"""Placement explainability (ISSUE 5).

Serial-vs-vectorized AllocMetric parity: the same jobs/nodes through
the oracle iterator chain and the kernel path must agree on
nodes_evaluated, nodes_filtered, per-reason constraint_filtered
totals, the exhaustion histograms, and the winner's normalized score
— the explain capture reconstructs the serial chain's metrics from
the kernel select's own outputs, so any drift is a bug.  Plus the
retention ring, the HTTP/CLI surfaces, the top-K score-meta trim, and
the zero-registered placement.* telemetry.
"""
import json
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.explain import (
    EXPLAIN,
    PLACEMENT_COUNTERS,
    PLACEMENT_GAUGES,
    alloc_metric_to_api,
    dimension_slug,
    reason_slug,
)
from nomad_tpu.sched.feasible import (
    FILTER_CLASS_INELIGIBLE,
    FILTER_CONSTRAINT_CSI_VOLUMES,
    FILTER_CONSTRAINT_DEVICES,
    FILTER_CONSTRAINT_DRIVERS,
    FILTER_CONSTRAINT_HOST_VOLUMES,
    FILTER_CONSTRAINT_NETWORK,
)
from nomad_tpu.sched.generic_sched import BatchScheduler, ServiceScheduler
from nomad_tpu.sched.testing import Harness
from nomad_tpu.structs import (
    AllocMetric,
    Constraint,
    NodeScoreMeta,
    compute_node_class,
)

from conftest import heterogeneous_cluster


def _placed_metrics(harness):
    """alloc name -> metric summary tuple for the last computed plan
    (read off the submitted plan so complete-failure runs, which
    never submit, yield {})."""
    out = {}
    plans = harness.plans[-1:] if harness.plans else []
    for plan in plans:
        for v in plan.node_allocation.values():
            for a in v:
                m = a.metrics
                out[a.name] = (
                    m.nodes_evaluated,
                    m.nodes_filtered,
                    m.nodes_exhausted,
                    dict(m.constraint_filtered),
                    dict(m.class_filtered),
                    dict(m.dimension_exhausted),
                    m.node_norm_score(a.node_id),
                )
    return out


def _failed_metrics(sched):
    out = {}
    for tg, m in sched.failed_tg_allocs.items():
        out[tg] = (
            m.nodes_evaluated,
            m.nodes_filtered,
            m.nodes_exhausted,
            dict(m.constraint_filtered),
            dict(m.class_filtered),
            dict(m.dimension_exhausted),
        )
    return out


def _score_meta(harness):
    out = {}
    plans = harness.plans[-1:] if harness.plans else []
    for plan in plans:
        for v in plan.node_allocation.values():
            for a in v:
                out[a.name] = sorted(
                    (
                        m.node_id,
                        tuple(sorted(m.scores.items())),
                        m.norm_score,
                    )
                    for m in a.metrics.score_meta
                )
    return out


def run_both(harness, factory, evaluation, seed):
    harness.reject_plan = True
    s_oracle = harness.process(
        factory, evaluation, use_tpu=False, seed=seed
    )
    oracle = (
        _placed_metrics(harness),
        _score_meta(harness),
        _failed_metrics(s_oracle),
    )
    s_tpu = harness.process(
        factory, evaluation, use_tpu=True, seed=seed
    )
    tpu = (
        _placed_metrics(harness),
        _score_meta(harness),
        _failed_metrics(s_tpu),
    )
    return oracle, tpu


# ---------------------------------------------------------------------------
# serial-vs-vectorized metric parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("trial", range(4))
def test_metric_parity_plain_service(harness, trial):
    heterogeneous_cluster(harness, 50, seed=trial)
    job = mock.job(datacenters=["dc1", "dc2"])
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    oracle, tpu = run_both(
        harness, ServiceScheduler, ev, seed=trial * 17 + 3
    )
    assert oracle == tpu
    # every placement recorded a full decomposition
    assert all(
        meta for meta in oracle[1].values()
    ), "oracle recorded empty score meta"


@pytest.mark.parametrize("trial", range(4))
def test_metric_parity_constraint_filtering(harness, trial):
    """Per-reason constraint_filtered totals — including the
    computed-class memoization ('computed class ineligible' after the
    first node of a known-bad class)."""
    heterogeneous_cluster(harness, 50, seed=trial + 200)
    job = mock.job(datacenters=["dc1", "dc2"])
    job.constraints = [
        Constraint("${attr.kernel.name}", "linux", "="),
        Constraint("${attr.os.version}", "2[02].04", "regexp"),
    ]
    job.task_groups[0].constraints = [
        Constraint("${attr.nomad.version}", ">= 0.9", "version"),
        Constraint("${attr.rack}", "r4", "!="),
    ]
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    oracle, tpu = run_both(
        harness, ServiceScheduler, ev, seed=trial * 7 + 1
    )
    assert oracle == tpu
    # the config actually exercised filtering
    any_filtered = any(
        t[1] > 0 for t in oracle[0].values()
    )
    assert any_filtered, "test config filtered nothing"


def test_metric_parity_class_memoization(harness):
    """All nodes share one computed class and fail a job constraint:
    the serial wrapper filters the first node on the constraint and
    the rest as 'computed class ineligible' — the capture must
    reproduce both."""
    nodes = []
    for i in range(8):
        n = mock.node()
        n.attributes["rack"] = "r9"
        n.computed_class = compute_node_class(n)
        harness.store.upsert_node(n)
        nodes.append(n)
    # one eligible node with a distinct class so placement succeeds
    good = mock.node()
    good.attributes["rack"] = "r1"
    good.node_class = "good"
    good.computed_class = compute_node_class(good)
    harness.store.upsert_node(good)
    job = mock.job()
    job.task_groups[0].count = 1
    job.constraints = [Constraint("${attr.rack}", "r9", "!=")]
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    oracle, tpu = run_both(harness, ServiceScheduler, ev, seed=5)
    assert oracle == tpu
    (metrics,) = oracle[0].values()
    reasons = metrics[3]
    if FILTER_CLASS_INELIGIBLE in reasons:
        # at least one same-class node after the first was memoized
        assert reasons[FILTER_CLASS_INELIGIBLE] >= 1


@pytest.mark.parametrize("trial", range(3))
def test_metric_parity_batch_multi_count(harness, trial):
    """Batch multi-count jobs serve picks from the look-ahead cache
    (one launch per group); the serve-side capture recomputes each
    pick's plan-adjusted state host-side and must still match the
    oracle placement-for-placement."""
    heterogeneous_cluster(harness, 40, seed=trial + 100)
    job = mock.batch_job(datacenters=["dc1", "dc2"])
    job.task_groups[0].count = 7
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id, type="batch")
    oracle, tpu = run_both(
        harness, BatchScheduler, ev, seed=trial * 13 + 5
    )
    assert oracle == tpu


def test_metric_parity_exhaustion_failure(harness):
    """A job too big for every node: failed_tg_allocs must agree on
    the full exhaustion histogram."""
    heterogeneous_cluster(harness, 30, seed=7)
    job = mock.job(datacenters=["dc1", "dc2"])
    job.task_groups[0].tasks[0].resources.cpu = 100000
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    oracle, tpu = run_both(harness, ServiceScheduler, ev, seed=3)
    assert oracle == tpu
    failed = oracle[2]["web"]
    assert failed[0] == 30  # every candidate evaluated
    assert failed[5].get("cpu") == 30  # all exhausted on cpu


def test_filter_totals_account_for_every_evaluated_node(harness):
    """Acceptance criterion: filter-reason totals equal
    nodes_evaluated - feasible_count (scored nodes + exhausted nodes
    close the books)."""
    heterogeneous_cluster(harness, 50, seed=31)
    job = mock.job(datacenters=["dc1", "dc2"])
    job.constraints = [Constraint("${attr.rack}", "r[0-2]", "regexp")]
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    harness.reject_plan = True
    harness.process(ServiceScheduler, ev, use_tpu=True, seed=9)
    for name, m in _placed_metrics(harness).items():
        evaluated, filtered, exhausted = m[0], m[1], m[2]
        assert sum(m[3].values()) == filtered
        scored = 0
        for v in harness.plans[-1].node_allocation.values():
            for a in v:
                if a.name == name:
                    scored = len(a.metrics.score_meta)
        assert filtered + exhausted == evaluated - scored


def test_explain_disabled_skips_capture(harness):
    """NOMAD_TPU_EXPLAIN=0: decisions identical, no vectorized-side
    metric reconstruction (nodes_evaluated stays 0 on the kernel
    path's successful selects)."""
    heterogeneous_cluster(harness, 40, seed=3)
    job = mock.batch_job(datacenters=["dc1", "dc2"])
    job.task_groups[0].count = 5
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id, type="batch")
    harness.reject_plan = True
    harness.process(BatchScheduler, ev, use_tpu=False, seed=21)
    oracle_placements = sorted(
        (a.name, a.node_id)
        for v in harness.plans[-1].node_allocation.values()
        for a in v
    )
    EXPLAIN.set_enabled(False)
    try:
        harness.process(BatchScheduler, ev, use_tpu=True, seed=21)
        tpu_placements = sorted(
            (a.name, a.node_id)
            for v in harness.plans[-1].node_allocation.values()
            for a in v
        )
        assert oracle_placements == tpu_placements
        evaluated = [
            a.metrics.nodes_evaluated
            for v in harness.plans[-1].node_allocation.values()
            for a in v
        ]
        assert all(n == 0 for n in evaluated)
    finally:
        EXPLAIN.set_enabled(True)


def test_allocation_time_stamped(harness):
    heterogeneous_cluster(harness, 20, seed=1)
    job = mock.job(datacenters=["dc1", "dc2"])
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    harness.reject_plan = True
    harness.process(ServiceScheduler, ev, use_tpu=False, seed=1)
    times = [
        a.metrics.allocation_time_s
        for v in harness.plans[-1].node_allocation.values()
        for a in v
    ]
    assert times and all(t > 0.0 for t in times)


# ---------------------------------------------------------------------------
# top-K score-meta trim (satellite)
# ---------------------------------------------------------------------------


class _FakeNode:
    def __init__(self, node_id):
        self.id = node_id
        self.node_class = ""


def test_top_score_meta_trims_to_k():
    m = AllocMetric()
    for i in range(20):
        m.score_node(_FakeNode(f"n{i:02d}"), "binpack", i * 0.01)
        m.score_node(
            _FakeNode(f"n{i:02d}"), "normalized-score", i * 0.01
        )
    top = m.top_score_meta()
    assert len(top) == AllocMetric.SCORE_META_TOP_K
    assert [t.node_id for t in top] == [
        "n15", "n16", "n17", "n18", "n19"
    ]
    # in-memory list stays complete (trim is on read)
    assert len(m.score_meta) == 20


def test_top_score_meta_retains_winner():
    m = AllocMetric()
    for i in range(10):
        m.score_node(
            _FakeNode(f"n{i}"), "normalized-score", i * 0.1
        )
    top = m.top_score_meta(winner_node_id="n0")
    assert len(top) == AllocMetric.SCORE_META_TOP_K
    assert "n0" in {t.node_id for t in top}
    # highest scorer still present
    assert "n9" in {t.node_id for t in top}


def test_top_score_meta_small_list_untouched():
    m = AllocMetric()
    m.score_meta.append(NodeScoreMeta(node_id="a", norm_score=1.0))
    assert [s.node_id for s in m.top_score_meta()] == ["a"]


def test_alloc_metric_to_api_shape():
    m = AllocMetric()
    m.nodes_evaluated = 3
    m.filter_node(None, "missing drivers")
    m.exhausted_node(None, "cpu")
    for i in range(8):
        m.score_node(
            _FakeNode(f"n{i}"), "normalized-score", i * 0.1
        )
    api = alloc_metric_to_api(m, winner_node_id="n1")
    for key in (
        "NodesEvaluated", "NodesFiltered", "NodesAvailable",
        "ClassFiltered", "ConstraintFiltered", "NodesExhausted",
        "ClassExhausted", "DimensionExhausted", "QuotaExhausted",
        "ScoreMetaData", "AllocationTime", "CoalescedFailures",
    ):
        assert key in api
    assert len(api["ScoreMetaData"]) == AllocMetric.SCORE_META_TOP_K
    assert "n1" in {s["NodeID"] for s in api["ScoreMetaData"]}


# ---------------------------------------------------------------------------
# reason vocabulary
# ---------------------------------------------------------------------------


def test_reason_slugs_cover_serial_vocabulary():
    """Every serial-chain reason string folds into a non-'other' slug,
    and every slug has a zero-registered counter."""
    cases = {
        FILTER_CLASS_INELIGIBLE: "class-ineligible",
        FILTER_CONSTRAINT_DRIVERS: "missing-drivers",
        FILTER_CONSTRAINT_DEVICES: "missing-devices",
        FILTER_CONSTRAINT_HOST_VOLUMES: "missing-host-volumes",
        FILTER_CONSTRAINT_CSI_VOLUMES: "missing-csi-plugins",
        FILTER_CONSTRAINT_NETWORK: "missing-network",
        "distinct_hosts": "distinct-hosts",
        "distinct_property: rack=r1 used by 2 allocs": (
            "distinct-property"
        ),
        'missing property "${meta.rack}"': "distinct-property",
        "${attr.rack} = r4": "constraint",
    }
    for reason, slug in cases.items():
        assert reason_slug(reason) == slug, reason
        assert f"placement.filtered.{slug}" in PLACEMENT_COUNTERS
    for dim, slug in {
        "cpu": "cpu",
        "memory": "memory",
        "disk": "disk",
        "network: port collision": "ports",
        "reserved port collision": "ports",
        "devices: no instances available": "devices",
        "bandwidth exceeded": "bandwidth",
    }.items():
        assert dimension_slug(dim) == slug, dim
        assert f"placement.exhausted.{slug}" in PLACEMENT_COUNTERS
    assert "placement.score_spread" in PLACEMENT_GAUGES
    assert "placement.winner_margin" in PLACEMENT_GAUGES


# ---------------------------------------------------------------------------
# end-to-end: retention ring, endpoints, CLI, telemetry
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def explain_world():
    from nomad_tpu.api import start_http_server
    from nomad_tpu.server import Server

    server = Server(
        num_schedulers=2, heartbeat_ttl=60.0, seed=33,
        nack_timeout=5.0,
    )
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    for _ in range(5):
        server.register_node(mock.node())
    job = mock.job(id="explainjob")
    server.register_job(job)
    assert server.drain_to_idle(20)
    deadline = time.time() + 10
    ev = None
    while time.time() < deadline and ev is None:
        for e in server.store.evals_by_job("default", "explainjob"):
            if e.status == "complete":
                ev = e
        time.sleep(0.1)
    assert ev is not None
    yield {"server": server, "base": base, "eval": ev}
    http.stop()
    server.stop()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def test_placement_endpoint_breakdown(explain_world):
    """A server-processed eval (whichever pipeline path took it) has
    a retained per-TG breakdown with winner, availability and
    evaluated accounting."""
    base, ev = explain_world["base"], explain_world["eval"]
    rec = _get(base, f"/v1/evaluation/{ev.id}/placement")
    assert rec["EvalID"] == ev.id
    assert rec["JobID"] == "explainjob"
    tg = rec["TaskGroups"]["web"]
    assert tg["Placed"] == 10
    assert tg["Winner"]
    m = tg["Metric"]
    assert m["NodesEvaluated"] > 0
    assert m["NodesAvailable"]  # by-dc histogram
    assert m["AllocationTime"] > 0.0
    assert 0 < len(m["ScoreMetaData"]) <= 5


def test_placement_endpoint_kernel_path_acceptance(explain_world):
    """Acceptance criterion: a kernel-path (TPUGenericStack)
    placement's endpoint payload has per-component terms whose mean
    (over appended terms, the documented normalization) equals the
    recorded normalized score, and filter-reason totals equal
    nodes_evaluated - feasible_count."""
    base = explain_world["base"]
    harness = Harness()
    heterogeneous_cluster(harness, 40, seed=77)
    job = mock.job(id="kernelexplain", datacenters=["dc1", "dc2"])
    job.constraints = [
        Constraint("${attr.rack}", "r[0-2]", "regexp")
    ]
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    harness.reject_plan = True
    scheduler = harness.process(
        ServiceScheduler, ev, use_tpu=True, seed=41
    )
    # the ring is process-wide: record the kernel-path run and read
    # it back through the server's HTTP surface
    EXPLAIN.record_eval(ev, scheduler)
    rec = _get(base, f"/v1/evaluation/{ev.id}/placement")
    tg = rec["TaskGroups"]["web"]
    m = tg["Metric"]
    assert m["NodesEvaluated"] > 0
    for sm in m["ScoreMetaData"]:
        appended = [
            v
            for k, v in sm["Scores"].items()
            if k != "normalized-score"
            and not (
                v == 0
                and k in (
                    "job-anti-affinity",
                    "node-reschedule-penalty",
                    "node-affinity",
                )
            )
        ]
        assert appended
        assert abs(
            sum(appended) / len(appended) - sm["NormScore"]
        ) < 1e-12
    # filter-reason totals equal nodes_evaluated - feasible_count
    assert sum(m["ConstraintFiltered"].values()) == m["NodesFiltered"]
    match = None
    for v in scheduler.plan.node_allocation.values():
        for a in v:
            mm = a.metrics
            if a.node_id == tg["Winner"] and (
                mm.nodes_evaluated,
                mm.nodes_filtered,
                mm.nodes_exhausted,
            ) == (
                m["NodesEvaluated"],
                m["NodesFiltered"],
                m["NodesExhausted"],
            ):
                match = mm
    assert match is not None
    assert (
        m["NodesFiltered"] + m["NodesExhausted"]
        == m["NodesEvaluated"] - len(match.score_meta)
    )


def test_placement_listing_and_trace_cross_reference(explain_world):
    base, ev = explain_world["base"], explain_world["eval"]
    recents = _get(base, "/v1/placements?limit=16")
    assert any(r["EvalID"] == ev.id for r in recents)
    rec = _get(base, f"/v1/evaluation/{ev.id}/placement")
    trace = _get(base, f"/v1/traces/{ev.id}")
    assert rec["TraceID"] == trace["trace_id"]
    assert (
        trace["attrs"].get("placement")
        == f"/v1/evaluation/{ev.id}/placement"
    )


def test_placement_endpoint_404_when_unknown(explain_world):
    base = explain_world["base"]
    try:
        _get(base, "/v1/evaluation/no-such-eval/placement")
    except urllib.error.HTTPError as exc:
        assert exc.code == 404
    else:
        raise AssertionError("expected 404")


def test_eval_endpoint_full_failed_tg_shape(explain_world):
    """/v1/evaluation/<id> mirrors the plan API's full Nomad
    FailedTGAllocs shape for a blocked eval."""
    server, base = explain_world["server"], explain_world["base"]
    job = mock.job(id="toolarge")
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].resources.cpu = 10**9
    server.register_job(job)
    assert server.drain_to_idle(20)
    blocked = [
        e
        for e in server.store.evals_by_job("default", "toolarge")
        if e.failed_tg_allocs
    ]
    assert blocked
    payload = _get(base, f"/v1/evaluation/{blocked[0].id}")
    failed = payload["FailedTGAllocs"]["web"]
    for key in (
        "NodesEvaluated", "NodesFiltered", "ClassFiltered",
        "ClassExhausted", "QuotaExhausted", "NodesAvailable",
        "ScoreMetaData", "AllocationTime", "CoalescedFailures",
        "DimensionExhausted", "ConstraintFiltered",
    ):
        assert key in failed
    # the walk evaluated candidates before failing (exhaustion
    # *attribution* depends on which pipeline path took the eval;
    # the serial/kernel paths' histograms are covered by
    # test_metric_parity_exhaustion_failure)
    assert failed["NodesEvaluated"] > 0


def test_plan_endpoint_full_failed_tg_shape(explain_world):
    from nomad_tpu.api.codec import job_to_dict

    base = explain_world["base"]
    job = mock.job(id="planfail")
    job.task_groups[0].tasks[0].resources.cpu = 10**9
    body = json.dumps({"Job": job_to_dict(job)}).encode()
    req = urllib.request.Request(
        base + "/v1/job/planfail/plan",
        data=body,
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        payload = json.loads(resp.read())
    failed = payload["FailedTGAllocs"]["web"]
    for key in (
        "ClassFiltered", "ClassExhausted", "QuotaExhausted",
        "NodesAvailable", "ScoreMetaData", "AllocationTime",
        "CoalescedFailures",
    ):
        assert key in failed


def test_placement_telemetry_zero_registered(explain_world):
    server = explain_world["server"]
    dump = server.metrics.dump()
    for name in PLACEMENT_COUNTERS:
        assert name in dump["counters"], name
    for name in PLACEMENT_GAUGES:
        assert name in dump["gauges"], name
    assert dump["counters"]["placement.explained"] >= 1.0


def test_cli_eval_explain_renders(explain_world, monkeypatch, capsys):
    from nomad_tpu.cli import main

    monkeypatch.setenv("NOMAD_ADDR", explain_world["base"])
    main(["eval", "explain", explain_world["eval"].id])
    out = capsys.readouterr().out
    assert "Task group 'web'" in out
    assert "NormScore" in out
    # winner marker present
    assert "*" in out


def test_cli_eval_explain_json(explain_world, monkeypatch, capsys):
    from nomad_tpu.cli import main

    monkeypatch.setenv("NOMAD_ADDR", explain_world["base"])
    main(["eval", "explain", "-json", explain_world["eval"].id])
    payload = json.loads(capsys.readouterr().out)
    assert payload["EvalID"] == explain_world["eval"].id


def test_debug_bundle_captures_placements(
    explain_world, monkeypatch, tmp_path
):
    import tarfile

    from nomad_tpu.cli import main

    monkeypatch.setenv("NOMAD_ADDR", explain_world["base"])
    out = tmp_path / "bundle.tar.gz"
    main(["operator", "debug", "-output", str(out)])
    with tarfile.open(out) as tar:
        names = tar.getnames()
    assert "nomad-debug/placements.json" in names
    assert "nomad-debug/traces.json" in names


def test_explain_ring_bounded():
    from nomad_tpu.explain import ExplainRecorder

    rec = ExplainRecorder(ring=8)
    rec.set_enabled(True)
    for i in range(20):
        rec.publish({"EvalID": f"e{i}", "TaskGroups": {}})
    assert len(rec.recent(limit=100)) == 8
    assert rec.get("e0") is None
    assert rec.get("e19") is not None
    # newest-wins per eval id: the superseded record leaves the
    # listing too, not just the index
    rec.publish({"EvalID": "e19", "TaskGroups": {}, "v": 2})
    assert rec.get("e19")["v"] == 2
    listed = [r for r in rec.recent(limit=100) if r["EvalID"] == "e19"]
    assert len(listed) == 1 and listed[0]["v"] == 2
