"""Extended API/CLI surface: job versions/revert/stable/summary,
jobs/parse, validate, alloc lifecycle, agent monitor + pprof, operator
autopilot/raft (reference job_endpoint.go Revert/Stable, jobs parse
endpoint, alloc_endpoint.go Stop, client_alloc_endpoint.go
Restart/Signal, command/agent/monitor, nomad/operator_endpoint.go).
"""
import json
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.server import Server


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def api():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=7)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    yield server, base
    http.stop()
    server.stop()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def _post(base, path, body, method="POST"):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# job versions / revert / stable / summary
# ---------------------------------------------------------------------------


def test_job_versions_and_revert(api):
    server, base = api
    server.register_node(mock.node())
    job = mock.job(id="vweb")
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)

    # v1: bump priority
    from dataclasses import replace

    v1 = replace(job, priority=80)
    server.register_job(v1)
    assert server.drain_to_idle(10)

    versions = _get(base, "/v1/job/vweb/versions")["Versions"]
    assert [v["version"] for v in versions] == [1, 0]

    # mark v0 stable, then revert to it
    _post(base, "/v1/job/vweb/stable",
          {"JobVersion": 0, "Stable": True})
    assert server.store.job_by_version("default", "vweb", 0).stable

    resp = _post(base, "/v1/job/vweb/revert", {"JobVersion": 0})
    assert resp["EvalID"]
    cur = server.store.job_by_id("default", "vweb")
    assert cur.version == 2
    assert cur.priority == job.priority  # v0 settings restored

    # reverting to the current version is a 400
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base, "/v1/job/vweb/revert", {"JobVersion": 2})
    assert exc.value.code == 400


def test_job_summary(api):
    server, base = api
    server.register_node(mock.node())
    job = mock.job(id="sweb")
    job.task_groups[0].count = 2
    server.register_job(job)
    assert server.drain_to_idle(10)
    s = _get(base, "/v1/job/sweb/summary")
    assert s["JobID"] == "sweb"
    tg = job.task_groups[0].name
    total = sum(s["Summary"][tg].values())
    assert total == 2


# ---------------------------------------------------------------------------
# parse + validate
# ---------------------------------------------------------------------------


def test_jobs_parse_endpoint(api):
    _server, base = api
    hcl = """
    job "parsed" {
      datacenters = ["dc1"]
      group "g" {
        count = 4
        task "t" { driver = "mock_driver" }
      }
    }
    """
    parsed = _post(base, "/v1/jobs/parse", {"JobHCL": hcl})
    assert parsed["id"] == "parsed"
    assert parsed["task_groups"][0]["count"] == 4

    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base, "/v1/jobs/parse", {"JobHCL": "job {{{"})
    assert exc.value.code == 400


def test_validate_job_endpoint(api):
    _server, base = api
    good = {"Job": {"ID": "ok", "TaskGroups": [
        {"Name": "g", "Count": 1,
         "Tasks": [{"Name": "t", "Driver": "mock_driver"}]}]}}
    resp = _post(base, "/v1/validate/job", good)
    assert resp["ValidationErrors"] == []

    bad = {"Job": {"ID": "", "TaskGroups": []}}
    resp = _post(base, "/v1/validate/job", bad)
    assert resp["ValidationErrors"]


# ---------------------------------------------------------------------------
# alloc lifecycle
# ---------------------------------------------------------------------------


def test_alloc_stop_endpoint(api):
    server, base = api
    server.register_node(mock.node())
    job = mock.job(id="stoppable")
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)
    alloc = server.store.allocs_by_job("default", "stoppable")[0]
    resp = _post(base, f"/v1/allocation/{alloc.id}/stop", {})
    assert resp["EvalID"]
    stored = server.store.alloc_by_id(alloc.id)
    assert stored.desired_status == "stop"


def test_alloc_restart_and_signal_proxy(api, tmp_path):
    from nomad_tpu.client import Client
    from nomad_tpu.structs import Node, Task

    server, base = api
    cli = Client(
        server, node=Node(), data_dir=str(tmp_path),
        heartbeat_interval=5.0,
    )
    cli.start()
    try:
        job = mock.job(id="sigjob")
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks[0] = Task(
            name="sleeper",
            driver="mock_driver",
            config={"run_for": 60},
        )
        server.register_job(job)
        assert server.drain_to_idle(10)
        allocs = server.store.allocs_by_job("default", "sigjob")
        assert wait_until(
            lambda: any(
                a.client_status == "running"
                for a in server.store.allocs_by_job(
                    "default", "sigjob"
                )
            )
        )
        alloc_id = allocs[0].id
        _post(
            base,
            f"/v1/client/allocation/{alloc_id}/signal",
            {"Signal": "SIGHUP", "TaskName": "sleeper"},
        )
        driver = cli.drivers["mock_driver"]
        assert wait_until(
            lambda: any(
                sig == "SIGHUP"
                for _tid, sig in getattr(driver, "signals", [])
            )
        ), "signal not delivered to driver"
        # restart: kills the running mock task; the runner restarts it
        _post(
            base,
            f"/v1/client/allocation/{alloc_id}/restart",
            {"TaskName": "sleeper"},
        )
        # 404 for unknown alloc
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base, "/v1/client/allocation/nope/restart", {})
        assert exc.value.code == 404
    finally:
        cli.stop()


# ---------------------------------------------------------------------------
# agent monitor + pprof
# ---------------------------------------------------------------------------


def test_agent_monitor_tail(api):
    server, base = api
    server.log_monitor.write_line("hello-from-monitor")
    resp = _get(base, "/v1/agent/monitor")
    assert any("hello-from-monitor" in l for l in resp["Lines"])
    seq = resp["Index"]
    # nothing new after the cursor
    resp2 = _get(base, f"/v1/agent/monitor?index={seq}")
    assert resp2["Lines"] == []
    server.log_monitor.write_line("second")
    resp3 = _get(base, f"/v1/agent/monitor?index={seq}")
    assert resp3["Lines"] == ["second"]


def test_agent_monitor_captures_logging(api):
    import logging

    server, base = api
    logging.getLogger("nomad_tpu.test").info("via-logging-%d", 42)
    resp = _get(base, "/v1/agent/monitor")
    assert any("via-logging-42" in l for l in resp["Lines"])


def test_pprof_analogs(api):
    _server, base = api
    prof = _get(base, "/v1/agent/pprof/goroutine")
    assert "thread" in prof["Profile"]
    heap = _get(base, "/v1/agent/pprof/heap")
    assert heap["Threads"] >= 1
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(base, "/v1/agent/pprof/bogus")
    assert exc.value.code == 404


# ---------------------------------------------------------------------------
# operator autopilot / raft
# ---------------------------------------------------------------------------


def test_operator_autopilot_requires_cluster(api):
    _server, base = api
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(base, "/v1/operator/autopilot/configuration")
    assert exc.value.code == 404


def test_operator_endpoints_on_cluster():
    from nomad_tpu.server.cluster import TestCluster

    c = TestCluster(3, heartbeat_ttl=60.0)
    c.start()
    http = None
    try:
        leader = c.wait_for_leader()
        http = start_http_server(leader, port=0)
        base = f"http://127.0.0.1:{http.port}"
        cfg = _get(base, "/v1/operator/autopilot/configuration")
        assert cfg["CleanupDeadServers"] is True
        _post(
            base,
            "/v1/operator/autopilot/configuration",
            {"CleanupDeadServers": False},
        )
        assert leader.autopilot.config.cleanup_dead_servers is False

        health = _get(base, "/v1/operator/autopilot/health")
        assert health["NumServers"] == 3
        assert health["Healthy"] is True

        raftcfg = _get(base, "/v1/operator/raft/configuration")
        assert len(raftcfg["Servers"]) == 3
        assert sum(1 for s in raftcfg["Servers"] if s["Leader"]) == 1
    finally:
        if http is not None:
            http.stop()
        c.stop()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_job_history_revert_and_monitor(api, monkeypatch, capsys):
    from nomad_tpu.cli import main

    server, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)
    server.register_node(mock.node())
    job = mock.job(id="cliweb")
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)
    from dataclasses import replace

    server.register_job(replace(job, priority=90))
    assert server.drain_to_idle(10)

    main(["job", "history", "cliweb"])
    out = capsys.readouterr().out
    assert "Version" in out and "1" in out

    main(["job", "revert", "cliweb", "0"])
    out = capsys.readouterr().out
    assert "Evaluation" in out

    main(["job", "inspect", "cliweb"])
    out = capsys.readouterr().out
    assert '"id": "cliweb"' in out

    server.log_monitor.write_line("cli-monitor-line")
    main(["monitor", "-no-follow"])
    out = capsys.readouterr().out
    assert "cli-monitor-line" in out

    main(["operator", "raft", "list-peers"])
    out = capsys.readouterr().out
    assert "Address" in out


def test_cli_alloc_lifecycle(api, monkeypatch, capsys):
    from nomad_tpu.cli import main

    server, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)
    server.register_node(mock.node())
    job = mock.job(id="clialloc")
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)
    alloc = server.store.allocs_by_job("default", "clialloc")[0]
    main(["alloc", "stop", alloc.id])
    out = capsys.readouterr().out
    assert "Evaluation" in out
    assert (
        server.store.alloc_by_id(alloc.id).desired_status == "stop"
    )


def test_cli_operator_debug(api, monkeypatch, capsys, tmp_path):
    import tarfile

    from nomad_tpu.cli import main

    server, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)
    out = str(tmp_path / "bundle.tar.gz")
    main(["operator", "debug", "-output", out])
    assert "Wrote debug bundle" in capsys.readouterr().out
    with tarfile.open(out) as tar:
        names = tar.getnames()
    assert "nomad-debug/agent-self.json" in names
    assert "nomad-debug/pprof-goroutine.json" in names
    assert "nomad-debug/metrics.json" in names


def test_blocking_queries(api):
    import threading

    server, base = api
    server.register_node(mock.node())
    # non-blocking when the index is already stale
    req = urllib.request.urlopen(
        base + "/v1/jobs?index=0&wait=5", timeout=10
    )
    assert req.headers.get("X-Nomad-Index") is not None
    idx = int(req.headers["X-Nomad-Index"])
    req.read()

    # blocks until a write advances the state
    got = {}

    def poll():
        t0 = time.monotonic()
        r = urllib.request.urlopen(
            base + f"/v1/jobs?index={idx}&wait=10", timeout=20
        )
        got["dt"] = time.monotonic() - t0
        got["jobs"] = json.loads(r.read())
        got["index"] = int(r.headers["X-Nomad-Index"])

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.3)
    server.register_job(mock.job(id="blockjob"))
    t.join(15)
    assert not t.is_alive()
    assert got["dt"] >= 0.25  # actually waited
    assert got["index"] > idx
    assert any(j["ID"] == "blockjob" for j in got["jobs"])

    # wait expiry returns current data; background scheduling may
    # advance the index concurrently, which also legitimately wakes it
    server.drain_to_idle(10)
    r0 = urllib.request.urlopen(base + "/v1/jobs", timeout=10)
    idx2 = int(r0.headers["X-Nomad-Index"])
    r0.read()
    t0 = time.monotonic()
    r = urllib.request.urlopen(
        base + f"/v1/jobs?index={idx2}&wait=0.4", timeout=10
    )
    dt = time.monotonic() - t0
    woke_index = int(r.headers["X-Nomad-Index"])
    r.read()
    assert dt < 5.0
    assert dt >= 0.3 or woke_index > idx2


# ---------------------------------------------------------------------------
# namespaces (reference nomad/namespace_endpoint; OSS'd in 1.0)
# ---------------------------------------------------------------------------


def test_namespace_lifecycle(api, monkeypatch, capsys):
    from nomad_tpu.cli import main

    server, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)

    # default always present
    nss = _get(base, "/v1/namespaces")
    assert [n["Name"] for n in nss] == ["default"]

    main(["namespace", "apply", "-description", "web team", "prod"])
    assert "applied" in capsys.readouterr().out
    n = _get(base, "/v1/namespace/prod")
    assert n["Description"] == "web team"

    main(["namespace", "list"])
    out = capsys.readouterr().out
    assert "prod" in out and "default" in out

    # jobs in an unknown namespace are rejected; known ones accepted
    bad = mock.job(id="nsjob")
    bad.namespace = "ghost"
    with pytest.raises(ValueError):
        server.register_job(bad)
    ok = mock.job(id="nsjob")
    ok.namespace = "prod"
    server.register_job(ok)

    # a namespace with jobs refuses deletion
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base, "/v1/namespace/prod", {}, method="DELETE")
    assert exc.value.code == 400

    server.deregister_job("prod", "nsjob", purge=True)
    main(["namespace", "delete", "prod"])
    assert "deleted" in capsys.readouterr().out
    assert [n["Name"] for n in _get(base, "/v1/namespaces")] == [
        "default"
    ]

    # default namespace can never be deleted
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base, "/v1/namespace/default", {}, method="DELETE")
    assert exc.value.code == 400


def test_acl_token_info_self_update(api):
    server, base = api
    # bootstrap management token
    boot = _post(base, "/v1/acl/bootstrap", {})
    assert boot["SecretID"]
    created = _post(
        base, "/v1/acl/tokens", {"Name": "t1", "Type": "client"}
    )
    acc = created["AccessorID"]

    info = _get(base, f"/v1/acl/token/{acc}")
    assert info["Name"] == "t1"

    _post(base, f"/v1/acl/token/{acc}", {"Name": "renamed"})
    assert _get(base, f"/v1/acl/token/{acc}")["Name"] == "renamed"

    # token self resolves the caller's own token
    req = urllib.request.Request(
        base + "/v1/acl/token/self",
        headers={"X-Nomad-Token": created["SecretID"]},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        me = json.loads(resp.read())
    assert me["AccessorID"] == acc


def test_cli_new_commands_smoke(api, monkeypatch, capsys, tmp_path):
    from nomad_tpu.cli import main

    server, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)
    server.register_node(mock.node())
    job = mock.job(id="smoke")
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)

    # top-level aliases
    main(["status"])
    assert "smoke" in capsys.readouterr().out
    main(["status", "smoke"])
    assert "smoke" in capsys.readouterr().out

    # job eval forces a fresh evaluation
    main(["job", "eval", "smoke"])
    out = capsys.readouterr().out
    assert "Created eval" in out
    assert server.drain_to_idle(10)

    # job deployments
    main(["job", "deployments", "smoke"])
    capsys.readouterr()

    # deployment list
    main(["deployment", "list"])
    capsys.readouterr()

    # job init writes the example file
    target = tmp_path / "example.nomad"
    main(["job", "init", str(target)])
    assert "Example job" in capsys.readouterr().out
    assert target.exists()

    # system reconcile summaries
    main(["system", "reconcile", "summaries"])
    assert "reconciled" in capsys.readouterr().out

    # operator snapshot save + inspect
    snap = tmp_path / "state.snap"
    main(["operator", "snapshot", "save", str(snap)])
    capsys.readouterr()
    main(["operator", "snapshot", "inspect", str(snap)])
    out = capsys.readouterr().out
    assert "Index" in out and "jobs" in out


def test_cli_long_tail_commands(api, monkeypatch, capsys):
    """Smoke the round-4 command additions (reference
    command/commands.go registrations): job allocs, volume detach,
    server force-leave alias surface, keygen/keyring, check, ui,
    raft remove-peer flag parsing, license/sentinel/quota OSS gates,
    hyphenated legacy aliases."""
    import base64

    import pytest as _pytest

    from nomad_tpu.cli import main

    server, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)
    node = mock.node()
    server.register_node(node)
    job = mock.job(id="tailweb")
    job.task_groups[0].count = 2
    server.register_job(job)
    assert server.drain_to_idle(10)

    main(["job", "allocs", "tailweb"])
    out = capsys.readouterr().out
    assert "Task Group" in out and "web" in out
    main(["job", "allocs", "-json", "tailweb"])
    assert "tailweb" in capsys.readouterr().out

    # keygen emits a 32-byte base64 key; keyring round-trips it
    main(["keygen"])
    key = capsys.readouterr().out.strip()
    assert len(base64.b64decode(key)) == 32
    main(["keyring", "-install", key])
    main(["operator", "keyring", "-list"])
    assert key in capsys.readouterr().out
    second = base64.b64encode(b"x" * 32).decode()
    main(["keyring", "-install", second])
    main(["keyring", "-use", second])
    main(["keyring", "-remove", key])
    capsys.readouterr()
    main(["keyring", "-list"])
    out = capsys.readouterr().out
    assert second in out and key not in out

    main(["check"])
    assert "ok" in capsys.readouterr().out
    main(["ui"])
    assert "/ui/" in capsys.readouterr().out

    # volume detach releases a node's claims
    from nomad_tpu import mock as _mock

    vol = _mock.csi_volume(plugin_id="p1")
    server.store.upsert_csi_volume(vol)
    alloc = server.store.allocs_by_job("default", "tailweb")[0]
    server.store.claim_csi_volume(
        "default", vol.id, alloc.id, alloc.node_id, False
    )
    main(["volume", "detach", vol.id, alloc.node_id])
    assert "Detached 1" in capsys.readouterr().out

    # hyphenated aliases route to the same commands
    main(["node-status"])
    assert node.id[:8] in capsys.readouterr().out
    main(["server-members"])
    capsys.readouterr()

    # OSS enterprise gates surface the server's 501
    for argv in (
        ["license", "get"],
        ["sentinel", "list"],
        ["quota", "list"],
    ):
        with _pytest.raises(SystemExit):
            main(argv)
        assert "Enterprise" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# client alloc-status push (ADVICE r4: no in-place store mutation)
# ---------------------------------------------------------------------------


def test_alloc_status_push_frees_node_usage(api):
    """POST /v1/node/<id>/allocs with a terminal ClientStatus must
    release the alloc's cpu/mem from the serving server's node table:
    the handler sends a COPY through the upsert so was_live is computed
    against the pre-update store object (ADVICE r4 high)."""
    server, base = api
    node = mock.node()
    server.register_node(node)
    job = mock.job(id="pushjob")
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)
    alloc = server.store.allocs_by_job("default", "pushjob")[0]
    row = server.store.node_table.row_of[alloc.node_id]
    assert server.store.node_table.cpu_used[row] > 0

    # mark running first (live -> live: usage unchanged)
    _post(
        base,
        f"/v1/node/{alloc.node_id}/allocs",
        {"Allocs": [{"ID": alloc.id, "ClientStatus": "running"}]},
    )
    assert server.store.node_table.cpu_used[row] > 0

    # live -> terminal: usage must drop to zero on THIS server
    _post(
        base,
        f"/v1/node/{alloc.node_id}/allocs",
        {"Allocs": [{"ID": alloc.id, "ClientStatus": "complete"}]},
    )
    assert server.store.node_table.cpu_used[row] == 0
    assert (
        server.store.alloc_by_id(alloc.id).client_status == "complete"
    )


def test_full_wire_alloc_update_preserves_server_intent(api):
    """A remote client's full wire-form alloc push must merge only
    the client-owned fields: a desired_status=stop staged by the
    server after the client's last pull must survive the push
    (review r5 — wholesale replace reverted drains/preemptions)."""
    from nomad_tpu.api.codec import alloc_to_dict

    server, base = api
    server.register_node(mock.node())
    job = mock.job(id="wirejob")
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)
    alloc = server.store.allocs_by_job("default", "wirejob")[0]

    # the client pulled this copy, then the server staged a stop
    stale = alloc_to_dict(alloc)
    stale["client_status"] = "running"
    stale["task_states"] = {
        "web": {"state": "running", "failed": False}
    }
    from dataclasses import replace as _rep

    server.store.upsert_allocs(
        [_rep(alloc, desired_status="stop")]
    )

    _post(
        base,
        f"/v1/node/{alloc.node_id}/allocs",
        {"Allocs": [stale]},
    )
    after = server.store.alloc_by_id(alloc.id)
    assert after.desired_status == "stop"  # intent preserved
    assert after.client_status == "running"  # client state merged
    assert after.task_states["web"].state == "running"
