"""CLI -json / -t output parity (reference: essentially every
status/list command supports both flags — command/job_status.go:22-40,
command/helpers.go Format).  Table-driven: every covered command must
emit valid JSON under -json and render a format-string under -t.
"""
import json
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.cli import main
from nomad_tpu.server import Server
from nomad_tpu.structs import Task


@pytest.fixture(scope="module")
def cli_world():
    """One populated cluster for the whole module: node, service job,
    alloc, eval, deployment, namespace, volume-free surface."""
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=11)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    node = mock.node()
    server.register_node(node)
    job = mock.job(id="fmtjob")
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0] = Task(
        name="web", driver="mock_driver", config={"run_for": -1}
    )
    server.register_job(job)
    assert server.drain_to_idle(10)
    alloc = server.store.allocs_by_job("default", "fmtjob")[0]
    ev = server.store.evals_by_job("default", "fmtjob")[0]
    yield {
        "server": server,
        "base": base,
        "node_id": node.id,
        "alloc_id": alloc.id,
        "eval_id": ev.id,
    }
    http.stop()
    server.stop()


# (argv-builder, template, expected-substring-from-template)
CASES = [
    (lambda w: ["job", "status", "-json"], None, None),
    (lambda w: ["job", "status", "-t", "{ID}|{Status}"], None, "fmtjob|"),
    (lambda w: ["job", "status", "-json", "fmtjob"], None, None),
    (
        lambda w: ["job", "status", "-t", "{id}/{type}", "fmtjob"],
        None,
        "fmtjob/service",
    ),
    (lambda w: ["job", "history", "-json", "fmtjob"], None, None),
    (
        lambda w: ["job", "history", "-t", "v{version}", "fmtjob"],
        None,
        "v0",
    ),
    (lambda w: ["job", "inspect", "-json", "fmtjob"], None, None),
    (lambda w: ["job", "allocs", "-json", "fmtjob"], None, None),
    (
        lambda w: ["job", "allocs", "-t", "{task_group}", "fmtjob"],
        None,
        "web",
    ),
    (lambda w: ["job", "deployments", "-json", "fmtjob"], None, None),
    (lambda w: ["node", "status", "-json"], None, None),
    (
        lambda w: ["node", "status", "-t", "{ID} {Status}"],
        None,
        "ready",
    ),
    (lambda w: ["node", "status", "-json", w["node_id"]], None, None),
    (
        lambda w: [
            "node", "status", "-t", "{name}={status}", w["node_id"]
        ],
        None,
        "=ready",
    ),
    (lambda w: ["node", "config", "-json", w["node_id"]], None, None),
    (lambda w: ["alloc", "status", "-json", w["alloc_id"]], None, None),
    (
        lambda w: [
            "alloc", "status", "-t", "{client_status}", w["alloc_id"]
        ],
        None,
        "",
    ),
    (lambda w: ["eval", "status", "-json", w["eval_id"]], None, None),
    (
        lambda w: [
            "eval", "status", "-t", "{status}", w["eval_id"]
        ],
        None,
        "complete",
    ),
    (lambda w: ["deployment", "list", "-json"], None, None),
    (lambda w: ["deployment", "status", "-json"], None, None),
    (lambda w: ["namespace", "list", "-json"], None, None),
    (
        lambda w: ["namespace", "list", "-t", "{Name}"],
        None,
        "default",
    ),
    (lambda w: ["namespace", "status", "-json", "default"], None, None),
    (lambda w: ["server", "members", "-json"], None, None),
    (
        lambda w: ["server", "members", "-t", "{Role}"],
        None,
        "server",
    ),
    (lambda w: ["plugin", "status", "-json"], None, None),
    (lambda w: ["scaling", "policies", "-json"], None, None),
    (
        lambda w: ["operator", "scheduler", "-json", "get-config"],
        None,
        None,
    ),
    (lambda w: ["operator", "raft", "list-peers", "-json"], None, None),
    (lambda w: ["agent-info", "-json"], None, None),
    (lambda w: ["volume", "status", "-json"], None, None),
    # hyphenated aliases carry the flags too
    (lambda w: ["node-status", "-json"], None, None),
    (lambda w: ["alloc-status", "-json", w["alloc_id"]], None, None),
    (lambda w: ["eval-status", "-json", w["eval_id"]], None, None),
    (lambda w: ["server-members", "-json"], None, None),
    (lambda w: ["status", "-json"], None, None),
]


@pytest.mark.parametrize("case_idx", range(len(CASES)))
def test_cli_format_flags(cli_world, monkeypatch, capsys, case_idx):
    build, _, expect = CASES[case_idx]
    argv = build(cli_world)
    monkeypatch.setenv("NOMAD_ADDR", cli_world["base"])
    main(argv)
    out = capsys.readouterr().out
    if "-json" in argv:
        data = json.loads(out)  # valid JSON, full payload
        assert data is not None
    else:
        assert expect in out


def test_cli_template_missing_field_errors(cli_world, monkeypatch, capsys):
    monkeypatch.setenv("NOMAD_ADDR", cli_world["base"])
    with pytest.raises(SystemExit):
        main(["job", "status", "-t", "{does_not_exist}", "fmtjob"])
    assert "missing field" in capsys.readouterr().err


def test_cli_template_nested_access(cli_world, monkeypatch, capsys):
    monkeypatch.setenv("NOMAD_ADDR", cli_world["base"])
    main(
        [
            "node", "status",
            "-t", "{node_resources[cpu]}",
            cli_world["node_id"],
        ]
    )
    out = capsys.readouterr().out.strip()
    assert out.isdigit() and int(out) > 0


def test_cli_template_list_traversal_case_tolerant(
    cli_world, monkeypatch, capsys
):
    monkeypatch.setenv("NOMAD_ADDR", cli_world["base"])
    main(
        ["job", "status", "-t", "{task_groups[0][Name]}", "fmtjob"]
    )
    assert capsys.readouterr().out.strip() == "web"


def test_cli_template_malformed_errors_cleanly(
    cli_world, monkeypatch, capsys
):
    monkeypatch.setenv("NOMAD_ADDR", cli_world["base"])
    with pytest.raises(SystemExit):
        main(["job", "status", "-t", "{id", "fmtjob"])
    assert "Error rendering template" in capsys.readouterr().err
