"""Continuous micro-batching tests: evals admitted into an in-flight
chunk chain must produce BIT-IDENTICAL decisions and AllocMetrics to
the same evals run in a fresh gulp (the serial-equivalence contract
extended across the admission boundary), under forced replay
conflicts and a mid-chain device failover included — plus unit
coverage of the admission gates, the adaptive chunk-width policy and
the single-deadline gulp fill.
"""
import copy
import random
import time

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.structs import compute_node_class


def make_nodes(n, seed=0):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node(id=f"cb-node-{seed}-{i}")
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def make_jobs(n, prefix="cb", seed=1):
    rng = random.Random(seed)
    jobs = []
    for i in range(n):
        job = mock.job(id=f"{prefix}-{i}")
        job.task_groups[0].count = rng.randint(1, 4)
        job.task_groups[0].tasks[0].resources.cpu = rng.choice(
            [200, 400]
        )
        jobs.append(job)
    return jobs


def placements(server, job_id):
    return sorted(
        (a.name, a.node_id)
        for a in server.store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    )


def eval_outcomes(server, job_id):
    """Terminal eval outcomes, decision-bearing fields only (eval ids
    are random per server)."""
    return sorted(
        (
            e.status,
            e.status_description,
            tuple(sorted(e.queued_allocations.items())),
        )
        for e in server.store.evals_by_job("default", job_id)
    )


def alloc_metrics(server, job_id):
    """Normalized AllocMetric view per eval of a job, from the explain
    ring: per-TG placements, winner and the full API-shape metric
    minus wall-clock fields."""
    from nomad_tpu.explain import EXPLAIN

    out = []
    for ev in sorted(
        server.store.evals_by_job("default", job_id),
        key=lambda e: e.create_index,
    ):
        rec = EXPLAIN.get(ev.id)
        if rec is None:
            out.append(None)
            continue
        tgs = {}
        for tg, entry in rec["TaskGroups"].items():
            metric = entry.get("Metric")
            if metric is not None:
                metric = {
                    k: v
                    for k, v in metric.items()
                    if k != "AllocationTime"
                }
            tgs[tg] = {
                "Placed": entry["Placed"],
                "Failed": entry["Failed"],
                "Winner": entry["Winner"],
                "Placements": sorted(
                    (
                        p["Name"],
                        p["NodeID"],
                        round(p["NormScore"], 9),
                    )
                    for p in entry["Placements"]
                ),
                "Metric": metric,
            }
        out.append(tgs)
    return out


def run_with_midchain_arrivals(jobs, split, seed=77, nodes_seed=3,
                               n_nodes=16, epoch_bump=False):
    """Batch server where jobs[:split] arrive as the gulp and
    jobs[split:] arrive while the first chain's chunk 0 is being
    launched (registered from inside the hooked _launch_chunk, so the
    admission poll deterministically sees them mid-chain).  With
    epoch_bump=True the hook additionally simulates a device failover
    right after the SECOND launch — i.e. after the late evals were
    admitted as chunk 2 of the chain — so the epoch check must drop
    the in-flight (admitted) chunk cleanly with zero lost evals."""
    server = Server(num_schedulers=1, seed=seed, batch_pipeline=True)
    worker = server.workers[0]
    late = [copy.deepcopy(j) for j in jobs[split:]]
    fired = []
    orig_launch = worker._launch_chunk

    def hooked(asm, c0, c1, carry, check_ready):
        fired.append(True)
        if len(fired) == 1:
            for job in late:
                server.register_job(job)
        out = orig_launch(asm, c0, c1, carry, check_ready)
        if epoch_bump and len(fired) == 2:
            worker._backend_epoch += 1
        return out

    worker._launch_chunk = hooked
    for node in make_nodes(n_nodes, seed=nodes_seed):
        server.register_node(copy.deepcopy(node))
    for job in jobs[:split]:
        server.register_job(copy.deepcopy(job))
    server.start()
    assert server.drain_to_idle(60)
    assert fired, "the hooked launch never ran (no chain launched)"
    return server


def run_fresh_gulps(jobs, split, seed=77, nodes_seed=3, n_nodes=16,
                    admit=True):
    """Reference server: the SAME evals in the SAME order, but as two
    flush-boundary gulps (drain between the halves, so nothing is
    ever admitted mid-chain)."""
    server = Server(num_schedulers=1, seed=seed, batch_pipeline=True)
    for node in make_nodes(n_nodes, seed=nodes_seed):
        server.register_node(copy.deepcopy(node))
    server.start()
    for job in jobs[:split]:
        server.register_job(copy.deepcopy(job))
    assert server.drain_to_idle(60)
    for job in jobs[split:]:
        server.register_job(copy.deepcopy(job))
    assert server.drain_to_idle(60)
    return server


def test_admission_parity_bit_identical_vs_fresh_gulp(monkeypatch):
    """The acceptance contract: evals admitted mid-chain produce
    bit-identical placements, eval outcomes AND AllocMetrics to the
    same evals run in fresh flush-boundary gulps.  Strict replay mode
    pins score-metric bit-identity (the relaxed default's documented
    envelope lets wave-contended node scores reflect the wave
    snapshot, which differs once the wave composition does —
    admission or not); decision/outcome parity in the relaxed default
    is covered by the other tests here."""
    monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    jobs = make_jobs(8, prefix="adm", seed=11)
    adm = run_with_midchain_arrivals(jobs, split=4, seed=77)
    try:
        fresh = run_fresh_gulps(jobs, split=4, seed=77)
        try:
            # metrics compared FIRST: the explain ring is process-wide
            # and bounded, so read before any other server churns it
            adm_metrics = {
                j.id: alloc_metrics(adm, j.id) for j in jobs
            }
            fresh_metrics = {
                j.id: alloc_metrics(fresh, j.id) for j in jobs
            }
            for job in jobs:
                assert placements(adm, job.id) == placements(
                    fresh, job.id
                ), f"placement divergence for {job.id}"
                assert eval_outcomes(adm, job.id) == eval_outcomes(
                    fresh, job.id
                ), f"eval outcome divergence for {job.id}"
                assert (
                    adm_metrics[job.id] == fresh_metrics[job.id]
                ), f"AllocMetric divergence for {job.id}"
            worker = adm.workers[0]
            # the contract is vacuous unless admission actually fired
            assert worker.admission_admitted > 0
            assert worker.admission_chains > 0
            assert (
                adm.metrics.get_counter("admission.admitted")
                == worker.admission_admitted
            )
        finally:
            fresh.stop()
    finally:
        adm.stop()


def test_admission_parity_under_forced_replay_conflicts(monkeypatch):
    """Admitted evals on a tiny contended cluster — where wave
    speculations lose their conflict checks and re-replay serially —
    must still match the fresh-gulp outcomes exactly."""
    monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    nodes_kw = dict(nodes_seed=9, n_nodes=4)
    jobs = make_jobs(10, prefix="conf", seed=13)
    for job in jobs:
        job.task_groups[0].count = 3
        job.task_groups[0].tasks[0].resources.cpu = 300
    adm = run_with_midchain_arrivals(
        jobs, split=5, seed=21, **nodes_kw
    )
    try:
        fresh = run_fresh_gulps(jobs, split=5, seed=21, **nodes_kw)
        try:
            for job in jobs:
                assert placements(adm, job.id) == placements(
                    fresh, job.id
                ), f"divergence for {job.id}"
                assert eval_outcomes(adm, job.id) == eval_outcomes(
                    fresh, job.id
                ), f"eval outcome divergence for {job.id}"
            worker = adm.workers[0]
            assert worker.admission_admitted > 0
            # strict mode on a 4-node cluster with every plan touching
            # the same nodes: the conflict path must actually engage
            assert worker.replay_conflicts > 0
        finally:
            fresh.stop()
    finally:
        adm.stop()


def test_admission_mid_chain_failover_drops_chain_cleanly():
    """A supervisor epoch bump mid-chain (device failover) drops the
    in-flight chain: every eval — gulped AND admitted — still
    completes with fresh-gulp-identical placements, zero lost."""
    jobs = make_jobs(8, prefix="flip", seed=17)
    adm = run_with_midchain_arrivals(
        jobs, split=4, seed=33, epoch_bump=True
    )
    try:
        fresh = run_fresh_gulps(jobs, split=4, seed=33)
        try:
            for job in jobs:
                assert placements(adm, job.id) == placements(
                    fresh, job.id
                ), f"divergence for {job.id}"
                assert eval_outcomes(adm, job.id) == eval_outcomes(
                    fresh, job.id
                ), f"eval outcome divergence for {job.id}"
            # the failover hit a chain that had actually admitted
            assert adm.workers[0].admission_admitted > 0
            # nothing stranded: the broker drained fully
            assert adm.broker.stats["total_unacked"] == 0
            assert adm.broker.stats["total_ready"] == 0
        finally:
            fresh.stop()
    finally:
        adm.stop()


def test_admission_opt_out_restores_flush_boundary_loop(monkeypatch):
    """NOMAD_TPU_ADMIT=0: arrivals mid-chain are never admitted (the
    next gulp picks them up) and outcomes still match."""
    monkeypatch.setenv("NOMAD_TPU_ADMIT", "0")
    jobs = make_jobs(6, prefix="optout", seed=19)
    adm = run_with_midchain_arrivals(jobs, split=3, seed=55)
    try:
        assert not adm.workers[0].admit_enabled
        assert adm.workers[0].admission_admitted == 0
        assert adm.metrics.get_gauge(
            "batch_worker.admit_enabled"
        ) == 0.0
        monkeypatch.delenv("NOMAD_TPU_ADMIT")
        fresh = run_fresh_gulps(jobs, split=3, seed=55)
        try:
            for job in jobs:
                assert placements(adm, job.id) == placements(
                    fresh, job.id
                ), f"divergence for {job.id}"
        finally:
            fresh.stop()
    finally:
        adm.stop()


def test_admission_defers_unbatchable_and_preserves_fifo():
    """A non-batchable arrival (sticky disk) mid-chain defers — and
    CLOSES the queue, so the batchable eval dequeued right after it
    cannot leapfrog the serial order.  Both still complete."""
    jobs = make_jobs(4, prefix="fifo", seed=23)
    sticky = mock.job(id="fifo-sticky")
    sticky.task_groups[0].ephemeral_disk.sticky = True
    tail = make_jobs(1, prefix="fifo-tail", seed=29)[0]

    server = Server(num_schedulers=1, seed=61, batch_pipeline=True)
    worker = server.workers[0]
    fired = []
    orig_launch = worker._launch_chunk

    def hooked(asm, c0, c1, carry, check_ready):
        if not fired:
            fired.append(True)
            server.register_job(copy.deepcopy(sticky))
            server.register_job(copy.deepcopy(tail))
        return orig_launch(asm, c0, c1, carry, check_ready)

    worker._launch_chunk = hooked
    for node in make_nodes(12, seed=7):
        server.register_node(node)
    for job in jobs:
        server.register_job(copy.deepcopy(job))
    server.start()
    try:
        assert server.drain_to_idle(60)
        assert fired
        assert worker.admission_deferred >= 1
        # everything placed despite the deferral
        assert len(placements(server, "fifo-sticky")) > 0
        assert len(placements(server, "fifo-tail-0")) > 0
        for job in jobs:
            assert len(placements(server, job.id)) > 0
    finally:
        server.stop()


def test_admission_gates_unit():
    """Gate-by-gate defer reasons, directly against a live store."""
    server = Server(num_schedulers=1, seed=5, batch_pipeline=True)
    worker = server.workers[0]
    for node in make_nodes(4, seed=41):
        server.register_node(node)
    server.start()
    try:
        job = make_jobs(1, prefix="gate", seed=43)[0]
        server.register_job(copy.deepcopy(job))
        assert server.drain_to_idle(30)
        ev = server.store.evals_by_job("default", job.id)[0]
        snap = server.store.snapshot()
        base = server.store.node_touch_counts()
        readiness = server.store.readiness_generation()
        live_job = server.store.job_by_id("default", job.id)

        def gates(**over):
            kw = dict(
                snap=snap, ev=ev, job=live_job,
                chain_jobs=set(), chain_base=base,
                wave_readiness=readiness,
                chain_epoch=worker._backend_epoch,
            )
            kw.update(over)
            return worker._admission_gates(**kw)

        # a drained job's eval passes every gate: its alloc-hosting
        # nodes are untouched relative to the fresh baseline
        assert gates() is None
        # strict-node: a baseline that disagrees with the live touch
        # count (the node was written after the chain snapshot) defers
        alloc_nodes = {
            a.node_id
            for a in snap.allocs_by_job("default", job.id)
        }
        assert alloc_nodes
        stale = dict(base)
        nid = next(iter(alloc_nodes))
        stale[nid] = stale.get(nid, 0) - 1
        assert gates(chain_base=stale) == "strict_node"
        # unbatchable shapes defer outright (sticky disk)
        sticky_job = copy.deepcopy(live_job)
        sticky_job.task_groups[0].ephemeral_disk.sticky = True
        assert gates(job=sticky_job) == "unbatchable"
        # a fresh job with no allocs passes every gate
        job2 = make_jobs(1, prefix="gate2", seed=47)[0]
        server.store.upsert_job(job2)
        snap2 = server.store.snapshot()
        ev2 = ev.__class__(
            namespace="default", job_id=job2.id, type="service",
            triggered_by="job-register",
        )
        live2 = server.store.job_by_id("default", job2.id)
        ok_kw = dict(
            snap=snap2, ev=ev2, job=live2,
            chain_base=server.store.node_touch_counts(),
            wave_readiness=server.store.readiness_generation(),
        )
        assert gates(**ok_kw) is None
        # same job already in the chain
        assert gates(
            **ok_kw, chain_jobs={("default", job2.id)}
        ) == "job_in_chain"
        # backend flipped since the chain was assembled
        assert gates(
            **ok_kw, chain_epoch=worker._backend_epoch + 1
        ) == "backend_flip"
        # readiness generation moved
        assert gates(**{
            **ok_kw,
            "wave_readiness": server.store.readiness_generation() - 1,
        }) == "readiness"
    finally:
        server.stop()


def test_plan_chunk_width_policy():
    """The adaptive chunk-width ladder: widest under backlog or with
    the budget off, sized-to-fit for small flushes, narrowed when the
    measured wide-launch cost would eat most of the budget."""
    server = Server(num_schedulers=1, seed=3, batch_pipeline=True)
    try:
        worker = server.workers[0]
        assert worker._chunk_buckets() == (2, 4, 8)
        # saturation or budget off: widest
        assert worker._plan_chunk_width(4, worker.batch_max) == 8
        worker.latency_budget_ms = 0.0
        assert worker._plan_chunk_width(2, 0) == 8
        worker.latency_budget_ms = 250.0
        # keeping up: smallest bucket covering the flush
        assert worker._plan_chunk_width(1, 0) == 2
        assert worker._plan_chunk_width(2, 0) == 2
        assert worker._plan_chunk_width(3, 0) == 4
        assert worker._plan_chunk_width(8, 0) == 8
        # fast wide launches: stay wide for big flushes
        worker._launch_ewma = {8: 20.0}
        assert worker._plan_chunk_width(30, 0) == 8
        # slow wide launches (> budget/2): narrow one bucket
        worker._launch_ewma = {8: 200.0}
        assert worker._plan_chunk_width(30, 0) == 4
        # the first measured warm launch seeds unmeasured buckets
        worker._launch_ewma = {}
        worker._launch_ewma_seed = None
        assert worker._launch_cost_ms(8) == 50.0
        worker._note_launch_cost(4, 12.0)
        assert worker._launch_ewma_seed == 12.0
        assert worker._launch_cost_ms(8) == 12.0
        worker._note_launch_cost(8, 40.0)
        assert worker._launch_ewma[8] == 40.0
        worker._note_launch_cost(8, 20.0)
        assert 20.0 < worker._launch_ewma[8] < 40.0
    finally:
        server.stop()


def test_gulp_fill_single_deadline():
    """The gulp fill waits ONE deadline total, not cap x BATCH_WAIT_S:
    a lone interactive eval is dequeued and processed without being
    held hostage to batch-fill timeouts."""
    from nomad_tpu.server.batch_worker import BATCH_WAIT_S

    server = Server(num_schedulers=1, seed=9, batch_pipeline=True)
    for node in make_nodes(6, seed=3):
        server.register_node(node)
    server.start()
    try:
        job = make_jobs(1, prefix="lone", seed=59)[0]
        waits = []
        broker = server.broker
        orig = broker.dequeue

        def timed(schedulers, timeout=None):
            if timeout is not None and timeout != 0.1:
                waits.append(timeout)
            return orig(schedulers, timeout=timeout)

        broker.dequeue = timed
        try:
            server.register_job(copy.deepcopy(job))
            assert server.drain_to_idle(30)
        finally:
            broker.dequeue = orig
        # every fill wait fits inside ONE BATCH_WAIT_S deadline
        # (admission polls pass timeout=0.0)
        assert all(w <= BATCH_WAIT_S + 1e-9 for w in waits), waits
        assert len(placements(server, job.id)) > 0
    finally:
        server.stop()


def test_admission_counters_zero_registered():
    """The admission.* family is visible on metrics dumps from
    construction (absence-of-series == admission never engaged)."""
    server = Server(num_schedulers=1, seed=2, batch_pipeline=True)
    try:
        counters = server.metrics.dump()["counters"]
        for name in (
            "admission.admitted",
            "admission.deferred",
            "admission.chains",
        ):
            assert name in counters, name
            assert counters[name] == 0.0
        assert (
            server.metrics.get_gauge("batch_worker.admit_enabled")
            == 1.0
        )
    finally:
        server.stop()


# -- sharded hot path (NOMAD_TPU_MESH=1) parity ------------------------
#
# The same admission harness, run with the admission server on the
# 8-device virtual CPU mesh (tests/conftest.py forces
# --xla_force_host_platform_device_count=8) and the reference server
# unsharded: decisions must be bit-identical sharded vs unsharded,
# INCLUDING chunked chains with mid-chain admission and forced replay
# conflicts — the acceptance contract for promoting the mesh path into
# the first-class pipeline.


def test_mesh_admission_parity_bit_identical_vs_unsharded(monkeypatch):
    """Evals admitted mid-chain into a SHARDED chunk chain produce
    bit-identical placements, outcomes and AllocMetrics to the same
    evals run unsharded in fresh flush-boundary gulps."""
    monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    jobs = make_jobs(8, prefix="madm", seed=11)
    monkeypatch.setenv("NOMAD_TPU_MESH", "1")
    adm = run_with_midchain_arrivals(jobs, split=4, seed=77)
    monkeypatch.setenv("NOMAD_TPU_MESH", "0")
    try:
        fresh = run_fresh_gulps(jobs, split=4, seed=77)
        try:
            adm_metrics = {
                j.id: alloc_metrics(adm, j.id) for j in jobs
            }
            fresh_metrics = {
                j.id: alloc_metrics(fresh, j.id) for j in jobs
            }
            for job in jobs:
                assert placements(adm, job.id) == placements(
                    fresh, job.id
                ), f"placement divergence for {job.id}"
                assert eval_outcomes(adm, job.id) == eval_outcomes(
                    fresh, job.id
                ), f"eval outcome divergence for {job.id}"
                assert (
                    adm_metrics[job.id] == fresh_metrics[job.id]
                ), f"AllocMetric divergence for {job.id}"
            worker = adm.workers[0]
            # both contracts are vacuous unless they actually fired:
            # the sharded runner dispatched AND admission spliced
            # chunks into its chain
            assert worker._mesh is not None
            assert worker.mesh_used > 0
            assert worker.admission_admitted > 0
            assert worker.timings["mesh_fetch"] > 0.0
            assert (
                adm.metrics.get_counter("mesh.launches") > 0
            )
        finally:
            fresh.stop()
    finally:
        adm.stop()


def test_mesh_admission_parity_under_forced_replay_conflicts(
    monkeypatch,
):
    """Sharded chains on a tiny contended cluster — wave speculations
    losing their conflict checks and re-replaying serially — must
    still match the unsharded fresh-gulp outcomes exactly."""
    monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    nodes_kw = dict(nodes_seed=9, n_nodes=4)
    jobs = make_jobs(10, prefix="mconf", seed=13)
    for job in jobs:
        job.task_groups[0].count = 3
        job.task_groups[0].tasks[0].resources.cpu = 300
    monkeypatch.setenv("NOMAD_TPU_MESH", "1")
    adm = run_with_midchain_arrivals(
        jobs, split=5, seed=21, **nodes_kw
    )
    monkeypatch.setenv("NOMAD_TPU_MESH", "0")
    try:
        fresh = run_fresh_gulps(jobs, split=5, seed=21, **nodes_kw)
        try:
            for job in jobs:
                assert placements(adm, job.id) == placements(
                    fresh, job.id
                ), f"divergence for {job.id}"
                assert eval_outcomes(adm, job.id) == eval_outcomes(
                    fresh, job.id
                ), f"eval outcome divergence for {job.id}"
            worker = adm.workers[0]
            assert worker.mesh_used > 0
            assert worker.admission_admitted > 0
            assert worker.replay_conflicts > 0
        finally:
            fresh.stop()
    finally:
        adm.stop()
