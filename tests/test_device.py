"""Accelerator supervisor tests: watchdog bounded calls, the
HEALTHY -> DEGRADED -> LOST -> RECOVERING state machine, hot CPU
failover under injected faults (zero dropped evals, decision parity,
flight-recorder incident traces), backend-cache invalidation, the
/v1/device surface, and the preflight module.

Everything runs on the CPU backend: ``NOMAD_TPU_FAULT`` makes the
failure modes deterministic, which is the whole point of the fault
hooks.
"""
import copy
import json
import random
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.device import (
    CPU_ONLY,
    DEGRADED,
    HEALTHY,
    LOST,
    RECOVERING,
    BudgetTracker,
    DeviceSupervisor,
    DeviceTimeout,
    FaultPlan,
    bounded_call,
)
from nomad_tpu.server import Server
from nomad_tpu.structs import compute_node_class
from nomad_tpu.telemetry import Metrics
from nomad_tpu.trace import SPAN_NAMES, TRACE


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def make_nodes(n, seed=0):
    rng = random.Random(seed)
    nodes = []
    for _ in range(n):
        node = mock.node()
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def make_jobs(n, prefix, seed=1):
    rng = random.Random(seed)
    jobs = []
    for i in range(n):
        job = mock.job(id=f"{prefix}-{i}")
        job.task_groups[0].count = rng.randint(1, 4)
        job.task_groups[0].tasks[0].resources.cpu = rng.choice(
            [200, 500]
        )
        jobs.append(job)
    return jobs


def placements(server, job_id):
    return sorted(
        (a.name, a.node_id)
        for a in server.store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    )


# -- watchdog primitives ------------------------------------------------


def test_bounded_call_passthrough_and_timeout():
    assert bounded_call(lambda: 41 + 1, 5.0) == 42
    with pytest.raises(ValueError):
        bounded_call(lambda: (_ for _ in ()).throw(ValueError("x")), 5.0)
    t0 = time.monotonic()
    with pytest.raises(DeviceTimeout) as exc:
        bounded_call(lambda: time.sleep(30), 0.2, stage="fetch")
    assert time.monotonic() - t0 < 5.0
    assert exc.value.stage == "fetch"


def test_bounded_call_reuses_worker_until_a_trip_burns_it():
    """Healthy guarded calls share one sacrificial thread per calling
    thread (no spawn on the hot path); a tripped deadline abandons it
    and the next call mints a replacement."""
    from nomad_tpu.device import watchdog

    assert bounded_call(lambda: 1, 5.0) == 1
    runner1 = watchdog._TLS.runner
    assert bounded_call(lambda: 2, 5.0) == 2
    assert watchdog._TLS.runner is runner1  # reused, not respawned
    with pytest.raises(DeviceTimeout):
        bounded_call(lambda: time.sleep(30), 0.2)
    assert runner1.dead
    assert bounded_call(lambda: 3, 5.0) == 3  # fresh runner
    assert watchdog._TLS.runner is not runner1


def test_budget_tracker_clamps_and_tracks():
    tracker = BudgetTracker(factor=10.0, min_s=1.0, max_s=5.0)
    # no history: the floor applies (a cold first launch must not trip
    # on its own compile)
    assert tracker.budget("launch") == 1.0
    tracker.note("launch", 0.3)
    assert tracker.budget("launch") == pytest.approx(3.0)
    tracker.note("launch", 100.0)  # EWMA moves, budget hits the cap
    assert tracker.budget("launch") == 5.0
    snap = tracker.snapshot()
    assert "launch" in snap and snap["launch"]["budget_s"] == 5.0


def test_fault_plan_parsing(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_FAULT", "wedge_launch,flaky:2")
    plan = FaultPlan.from_env()
    assert plan.active
    assert plan.describe() == ["flaky:2", "wedge_launch"]
    monkeypatch.setenv("NOMAD_TPU_FAULT", "typo_kind")
    with pytest.raises(ValueError):
        FaultPlan.from_env()
    monkeypatch.delenv("NOMAD_TPU_FAULT")
    assert not FaultPlan.from_env().active


# -- state machine ------------------------------------------------------


def test_cpu_only_supervisor_is_inert():
    sup = DeviceSupervisor(metrics=Metrics())
    assert sup.state() == CPU_ONLY
    assert not sup.expected
    assert not sup.failed_over()
    # guard is a pure passthrough — no sacrificial thread, no budget
    assert sup.guard("launch", lambda: "ok") == "ok"
    sup.start()  # must not spawn a probe thread
    assert sup._thread is None
    sup.trip("manual")  # no accelerator -> nothing to lose
    assert sup.state() == CPU_ONLY


def test_state_machine_flaky_roundtrip_with_injected_canary():
    calls = {"n": 0}

    def canary():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise RuntimeError("flaky canary")
        return 1.0

    metrics = Metrics()
    sup = DeviceSupervisor(
        metrics=metrics,
        expected=True,
        canary=canary,
        probe_interval_s=0.01,
        probe_timeout_s=2.0,
        lost_probes=2,
        recover_canaries=2,
    )
    states = []
    for _ in range(7):
        sup.probe_once()
        states.append(sup.state())
    assert states[:5] == [DEGRADED, DEGRADED, LOST, RECOVERING, HEALTHY]
    assert sup.failover_count == 1 and sup.recovered_count == 1
    # one epoch per flip: failover + restore
    assert sup.backend_epoch == 2
    assert metrics.get_gauge("device.state") == 1.0
    assert metrics.get_counter("device.failover") == 1.0
    assert metrics.get_counter("device.canary_fail") == 3.0
    # the incident trace closed with the recovery
    trace = TRACE.get(sup.last_incident)
    assert trace is not None and trace["outcome"] == "recovered"
    names = [s["name"] for s in trace["spans"]]
    assert "device.failover" in names and "device.recover" in names


def test_probe_timeout_is_an_immediate_wedge():
    sup = DeviceSupervisor(
        metrics=Metrics(),
        expected=True,
        canary=lambda: time.sleep(30),
        probe_interval_s=60.0,
        probe_timeout_s=0.2,
        init_grace_s=0.2,
    )
    assert not sup.probe_once()
    # a canary that BLOCKS is a wedge: straight to LOST, no DEGRADED
    assert sup.state() == LOST
    assert sup.probe_timeouts == 1
    sup.stop()


def test_warm_hooks_run_after_restore_flip():
    order = []
    calls = {"n": 0}

    def canary():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("down")
        return 1.0

    sup = DeviceSupervisor(
        metrics=Metrics(),
        expected=True,
        canary=canary,
        probe_interval_s=0.01,
        probe_timeout_s=2.0,
        lost_probes=1,
        recover_canaries=1,
    )
    sup.add_warm_hook(lambda: order.append(("warm", None)))
    sup.subscribe(
        lambda old, new, reason: order.append(("flip", new))
    )
    sup.probe_once()  # fail -> DEGRADED
    assert sup.state() == DEGRADED
    sup.probe_once()  # fail streak 2 >= 1+lost_probes -> LOST
    assert sup.state() == LOST
    sup.probe_once()  # ok -> RECOVERING
    assert sup.state() == RECOVERING
    sup.probe_once()  # ok -> HEALTHY flip, then re-warm hooks
    assert sup.state() == HEALTHY
    # listener flips fired for both failover and restore, and the
    # re-warm ran AFTER the restore flip — the hooks must compile for
    # the restored backend under the post-restore epoch (before the
    # flip they would target the CPU fallback and the flush would
    # discard every warmed shape)
    assert ("flip", LOST) in order and ("flip", HEALTHY) in order
    assert order.index(("warm", None)) > order.index(
        ("flip", HEALTHY)
    )


# -- forced-wedge failover soak ----------------------------------------


def test_wedge_launch_failover_soak(monkeypatch):
    """Under NOMAD_TPU_FAULT=wedge_launch a 64-eval soak must complete
    with zero lost/duplicated evals, decisions bit-identical to an
    unfaulted CPU run, detection well under 10s, and a well-nested
    device.failover trace naming the tripped watchdog."""
    nodes = make_nodes(20)
    jobs = make_jobs(64, "wedge")

    plain = Server(num_schedulers=1, seed=5, batch_pipeline=True)
    plain.start()
    try:
        assert plain.device_supervisor.state() == CPU_ONLY
        for node in nodes:
            plain.register_node(copy.deepcopy(node))
        for job in jobs:
            plain.register_job(copy.deepcopy(job))
        assert plain.drain_to_idle(60)
        plain_p = {j.id: placements(plain, j.id) for j in jobs}
    finally:
        plain.stop()

    monkeypatch.setenv("NOMAD_TPU_FAULT", "wedge_launch")
    monkeypatch.setenv("NOMAD_TPU_WATCHDOG_MIN_S", "0.5")
    monkeypatch.setenv("NOMAD_TPU_WATCHDOG_MAX_S", "0.5")
    # no real backend init to grace here — the wedge must trip at the
    # 0.5s budget, not after the 600s cold-start grace
    monkeypatch.setenv("NOMAD_TPU_INIT_GRACE_S", "0.5")
    # keep the (wedged) canary out of the picture: the launch watchdog
    # is what must detect this fault
    monkeypatch.setenv("NOMAD_TPU_PROBE_INTERVAL_S", "60")
    faulted = Server(num_schedulers=1, seed=5, batch_pipeline=True)
    faulted.start()
    try:
        sup = faulted.device_supervisor
        assert sup.expected and sup.state() == HEALTHY
        wall0 = time.time()
        for node in nodes:
            faulted.register_node(copy.deepcopy(node))
        for job in jobs:
            faulted.register_job(copy.deepcopy(job))
        assert faulted.drain_to_idle(90)
        # detection: the watchdog tripped the supervisor, failing the
        # pipeline over — well under the 10s acceptance bound
        assert sup.state() == LOST
        assert sup.failover_count == 1
        assert sup.watchdog_trips >= 1
        lost_at = next(
            h["at"]
            for h in sup.status()["history"]
            if h["to"] == LOST
        )
        assert lost_at - wall0 < 10.0
        # zero lost/duplicated evals: every eval completed exactly once
        evs = [
            e
            for e in faulted.store.evals.values()
            if e.job_id.startswith("wedge-")
        ]
        assert len(evs) >= 64
        assert all(e.status == "complete" for e in evs)
        # decision parity with the unfaulted CPU run
        for job in jobs:
            assert placements(faulted, job.id) == plain_p[job.id], (
                f"divergence for {job.id}"
            )
        # the worker flushed + re-keyed onto the CPU backend
        worker = faulted.workers[0]
        assert worker._backend_epoch == sup.backend_epoch == 1
        assert worker._usage_cache is None or (
            worker._usage_cache["key"][0] == 1
        )
        # the failover incident trace: recorded, well-nested, and
        # naming the tripped watchdog
        trace = TRACE.get(sup.last_incident)
        assert trace is not None
        names = [s["name"] for s in trace["spans"]]
        assert names[0] == "device.incident"
        assert "device.failover" in names
        failover = next(
            s for s in trace["spans"] if s["name"] == "device.failover"
        )
        assert failover["attrs"]["watchdog"] == "launch"
        ids = {s["id"] for s in trace["spans"]}
        for span in trace["spans"]:
            assert span["name"] in SPAN_NAMES
            assert span["dur_ms"] is not None  # nothing left open
            assert span["parent"] is None or span["parent"] in ids
        # /v1/device reflects it all
        status = sup.status()
        assert status["backend"] == "cpu"
        assert status["failover_count"] == 1
        assert status["faults"] == ["wedge_launch"]
    finally:
        faulted.stop()


def test_flaky_fault_roundtrip_reenables_device_path(monkeypatch):
    """NOMAD_TPU_FAULT=flaky round-trips LOST -> RECOVERING -> HEALTHY
    through the live probe thread and re-enables the device path,
    all visible via /v1/device and the device.state gauge."""
    from nomad_tpu.api import start_http_server

    monkeypatch.setenv("NOMAD_TPU_FAULT", "flaky:3")
    monkeypatch.setenv("NOMAD_TPU_PROBE_INTERVAL_S", "0.03")
    monkeypatch.setenv("NOMAD_TPU_PROBE_TIMEOUT_S", "5")
    monkeypatch.setenv("NOMAD_TPU_LOST_PROBES", "2")
    monkeypatch.setenv("NOMAD_TPU_RECOVER_CANARIES", "2")
    # guards stay active while HEALTHY; a cold CPU compile must not
    # masquerade as a wedge
    monkeypatch.setenv("NOMAD_TPU_WATCHDOG_MIN_S", "60")
    server = Server(num_schedulers=1, seed=3, batch_pipeline=True)
    server.start()
    http = start_http_server(server, port=0)
    try:
        sup = server.device_supervisor
        assert wait_until(
            lambda: sup.recovered_count >= 1
            and sup.state() == HEALTHY,
            timeout=15.0,
        ), sup.status()
        visited = {h["to"] for h in sup.status()["history"]}
        assert {DEGRADED, LOST, RECOVERING, HEALTHY} <= visited
        # device path re-enabled, worker re-keyed (failover + restore;
        # the listener runs synchronously on the probe thread, so give
        # it a beat past the state read)
        assert sup.device_available()
        assert wait_until(
            lambda: server.workers[0]._backend_epoch == 2, 5.0
        )
        assert server.metrics.get_gauge("device.state") == 1.0
        # the pipeline still schedules after the round trip
        for node in make_nodes(8):
            server.register_node(node)
        for job in make_jobs(4, "flaky"):
            server.register_job(job)
        assert server.drain_to_idle(30)
        assert placements(server, "flaky-0")
        # /v1/device over HTTP
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/v1/device"
        ) as resp:
            body = json.loads(resp.read())
        assert body["state"] == HEALTHY
        assert body["failover_count"] == 1
        assert body["recovered_count"] == 1
        assert body["enabled"] is True
    finally:
        http.stop()
        server.stop()


def test_slow_fetch_trips_the_fetch_watchdog(monkeypatch):
    """slow_fetch outlives the fetch budget (without wedging forever):
    the deadline monitor must trip rather than stall the gulp, and the
    evals still complete on the fallback path."""
    monkeypatch.setenv("NOMAD_TPU_FAULT", "slow_fetch")
    monkeypatch.setenv("NOMAD_TPU_WATCHDOG_MIN_S", "0.4")
    monkeypatch.setenv("NOMAD_TPU_WATCHDOG_MAX_S", "0.4")
    monkeypatch.setenv("NOMAD_TPU_INIT_GRACE_S", "0.4")
    monkeypatch.setenv("NOMAD_TPU_PROBE_INTERVAL_S", "60")
    server = Server(num_schedulers=1, seed=9, batch_pipeline=True)
    server.start()
    try:
        for node in make_nodes(12):
            server.register_node(node)
        for job in make_jobs(8, "slowfetch"):
            server.register_job(job)
        assert server.drain_to_idle(60)
        sup = server.device_supervisor
        assert sup.state() == LOST
        assert sup.watchdog_trips >= 1
        assert any(
            "watchdog:fetch" in h["reason"]
            for h in sup.status()["history"]
        )
        # the fallback path still placed work
        assert sum(
            len(placements(server, f"slowfetch-{i}"))
            for i in range(8)
        ) > 0
        evs = [
            e
            for e in server.store.evals.values()
            if e.job_id.startswith("slowfetch-")
        ]
        assert all(e.status == "complete" for e in evs)
    finally:
        server.stop()


# -- backend-cache invalidation ----------------------------------------


def test_failover_flushes_backend_keyed_caches(monkeypatch):
    """A supervisor transition must flush the device mirror, the
    host-assembly LRUs and the compiled-shape shield, and bump the
    backend epoch that keys them — a failover can never replay stale
    device buffers."""
    monkeypatch.setenv("NOMAD_TPU_SUPERVISOR", "1")
    monkeypatch.setenv("NOMAD_TPU_PROBE_INTERVAL_S", "3600")
    monkeypatch.setenv("NOMAD_TPU_WATCHDOG_MIN_S", "60")
    nodes = make_nodes(10)
    server = Server(num_schedulers=1, seed=2, batch_pipeline=True)
    server.start()
    try:
        worker = server.workers[0]
        sup = server.device_supervisor
        for node in nodes:
            server.register_node(copy.deepcopy(node))
        for job in make_jobs(6, "flush-a"):
            server.register_job(job)
        assert server.drain_to_idle(30)
        assert worker.prescored > 0
        assert len(worker._mask_cache) > 0
        assert worker._usage_cache is not None
        assert worker._usage_cache["key"][0] == 0
        mask_cache_before = worker._mask_cache

        sup.trip("manual")
        assert sup.state() == LOST
        assert worker._backend_epoch == 1
        assert worker._usage_cache is None
        assert worker._mask_cache is not mask_cache_before
        assert len(worker._mask_cache) == 0
        assert len(worker._cand_cache) == 0
        with worker._compile_lock:
            assert not worker._compiled

        # post-failover scheduling repopulates onto the new epoch and
        # still matches an independent reference run
        for job in make_jobs(6, "flush-b", seed=4):
            server.register_job(job)
        assert server.drain_to_idle(30)
        assert worker._usage_cache is not None
        assert worker._usage_cache["key"][0] == 1

        ref = Server(num_schedulers=1, seed=2, batch_pipeline=False)
        ref.start()
        try:
            for node in nodes:
                ref.register_node(copy.deepcopy(node))
            for job in make_jobs(6, "flush-a"):
                ref.register_job(job)
            assert ref.drain_to_idle(30)
            for job in make_jobs(6, "flush-b", seed=4):
                ref.register_job(job)
            assert ref.drain_to_idle(30)
            for i in range(6):
                assert placements(server, f"flush-a-{i}") == (
                    placements(ref, f"flush-a-{i}")
                )
                assert placements(server, f"flush-b-{i}") == (
                    placements(ref, f"flush-b-{i}")
                )
        finally:
            ref.stop()
    finally:
        server.stop()


def test_failover_listener_survives_wedged_usage_lock_holder(
    monkeypatch,
):
    """A wedged sacrificial thread can be abandoned while HOLDING
    _usage_cache_lock (it was parked inside _device_columns).  The
    failover listener runs on the thread the watchdog just protected,
    so it must never block on that lock — the flush uses a bare
    atomic assignment instead."""
    monkeypatch.setenv("NOMAD_TPU_SUPERVISOR", "1")
    monkeypatch.setenv("NOMAD_TPU_PROBE_INTERVAL_S", "3600")
    server = Server(num_schedulers=1, seed=1, batch_pipeline=True)
    server.start()
    try:
        worker = server.workers[0]
        wedged_lock = worker._usage_cache_lock
        assert wedged_lock.acquire(timeout=1)
        try:
            t0 = time.monotonic()
            server.device_supervisor.trip("launch")
            assert time.monotonic() - t0 < 2.0
            assert server.device_supervisor.state() == LOST
            assert worker._backend_epoch == 1
            assert worker._usage_cache is None
            # the lock itself was replaced, so post-failover CPU-path
            # _device_columns never queues behind the wedged holder
            assert worker._usage_cache_lock is not wedged_lock
            for node in make_nodes(4):
                server.register_node(node)
            t0 = time.monotonic()
            cols = worker._device_columns(
                server.store.node_table
            )
            assert cols is not None
            assert time.monotonic() - t0 < 5.0
        finally:
            wedged_lock.release()
    finally:
        server.stop()


# -- metrics + preflight -----------------------------------------------


def test_device_metrics_preregistered():
    """The whole device.* family is on /v1/metrics (and the prometheus
    scrape) from server construction — absence-of-series must never be
    confusable with absence-of-failures."""
    server = Server(num_schedulers=1, batch_pipeline=True)
    try:
        text = server.metrics.prometheus_text()
        for name in (
            "device_state",
            "device_backend_epoch",
            "device_failover",
            "device_canary_ok",
            "device_watchdog_trips",
            "device_probe_latency_ms_count",
        ):
            assert name in text, name
        dump = server.metrics.dump()
        assert dump["gauges"]["device.state"] == 0.0  # CPU_ONLY
        assert dump["counters"]["device.failover"] == 0.0
    finally:
        server.stop()


def test_preflight_healthy_on_cpu(capsys):
    from nomad_tpu.device import preflight

    result = preflight.run_preflight(total_s=30.0)
    assert result["state"] == HEALTHY
    assert result["attempts"] == 1
    assert preflight.main(["--budget-s", "30"]) == 0
    out = capsys.readouterr().out
    line = next(
        l for l in out.splitlines() if l.startswith("DEVICE_PREFLIGHT ")
    )
    payload = json.loads(line.split(" ", 1)[1])
    assert payload["state"] == HEALTHY


def test_preflight_init_block_unreachable(monkeypatch):
    from nomad_tpu.device import preflight

    monkeypatch.setenv("NOMAD_TPU_FAULT", "init_block")
    monkeypatch.setenv("NOMAD_TPU_PROBE_TIMEOUT_S", "0.2")
    t0 = time.monotonic()
    result = preflight.run_preflight(total_s=0.6)
    assert result["state"] == preflight.UNREACHABLE
    assert result["attempts"] >= 1
    assert time.monotonic() - t0 < 10.0
    assert preflight.main(["--budget-s", "0.6"]) == 2


def test_device_endpoint_idle_supervisor():
    from nomad_tpu.api import start_http_server

    server = Server(num_schedulers=1, batch_pipeline=True)
    server.start()
    http = start_http_server(server, port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/v1/device"
        ) as resp:
            body = json.loads(resp.read())
        assert body["enabled"] is False
        assert body["state"] == CPU_ONLY
        assert body["failover_count"] == 0
    finally:
        http.stop()
        server.stop()
