"""Overload-graceful control plane: admission backpressure (429 +
Retry-After by priority class, mode ladder), batched mass node-death
storm recovery, and the broker backlog signals feeding both."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.server import Server
from nomad_tpu.server.overload import (
    MODE_EMERGENCY,
    MODE_NORMAL,
    MODE_SHEDDING,
    PRI_HEARTBEAT,
    PRI_QUERY,
    PRI_SUBMIT,
    classify_request,
)
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_RUNNING,
    Evaluation,
    NODE_STATUS_DOWN,
)


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, dict(resp.getheaders()), json.loads(resp.read())


def _flood_broker(server, n):
    """Park n evals in the ready backlog (workers must be stopped)."""
    evals = [Evaluation(job_id=f"flood-{i}") for i in range(n)]
    server.store.upsert_evals(evals)
    server.broker.enqueue_all(evals)


def _stopped_server(**kw):
    """Started server whose workers are stopped, so broker backlog
    accumulates deterministically."""
    server = Server(
        num_schedulers=1, heartbeat_ttl=60.0, seed=7,
        batch_pipeline=False, **kw,
    )
    server.start()
    for w in server.workers:
        w.stop()
    return server


# -- broker signals ----------------------------------------------------


def test_broker_pending_depth_and_oldest_age():
    server = _stopped_server()
    try:
        broker = server.broker
        assert broker.pending_depth() == 0
        assert broker.oldest_pending_age() == 0.0
        _flood_broker(server, 5)
        assert broker.pending_depth() == 5
        time.sleep(0.05)
        age = broker.oldest_pending_age()
        assert age > 0.0
        # same-job evals park in the per-job pending heap and still
        # count toward the accepted-but-unstarted depth
        dup = [Evaluation(job_id="flood-0") for _ in range(3)]
        server.store.upsert_evals(dup)
        broker.enqueue_all(dup)
        assert broker.pending_depth() == 8
        # dequeues drain the age tracker
        ev, token = broker.dequeue(["service"], timeout=1.0)
        assert ev is not None
        assert broker.pending_depth() == 7
        broker.nack(ev.id, token)
    finally:
        server.stop()


# -- priority classes --------------------------------------------------


def test_classify_request_priority_classes():
    assert classify_request("POST", "/v1/node/abc/heartbeat") == PRI_HEARTBEAT
    assert classify_request("POST", "/v1/node/register") == PRI_HEARTBEAT
    assert classify_request("PUT", "/v1/node/abc/allocs") == PRI_HEARTBEAT
    assert classify_request("GET", "/v1/jobs") == PRI_QUERY
    assert classify_request("POST", "/v1/job/web/plan") == PRI_QUERY
    assert classify_request("POST", "/v1/search") == PRI_QUERY
    assert classify_request("POST", "/v1/jobs") == PRI_SUBMIT
    assert classify_request("DELETE", "/v1/job/web") == PRI_SUBMIT
    # observability is exempt — never shed
    assert classify_request("GET", "/v1/metrics") is None
    assert classify_request("GET", "/v1/overload") is None
    assert classify_request("GET", "/v1/device") is None


# -- mode ladder -------------------------------------------------------


def test_mode_ladder_escalates_and_recovers(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_DEPTH", "4")
    server = _stopped_server()
    try:
        ctl = server.overload
        assert ctl.evaluate(force=True) == MODE_NORMAL
        _flood_broker(server, 6)  # >= 4, < 16
        assert ctl.evaluate(force=True) == MODE_SHEDDING
        _flood_broker(server, 20)  # total 26 >= 4x4
        assert ctl.evaluate(force=True) == MODE_EMERGENCY
        # incident trace opened on the excursion
        from nomad_tpu.trace import TRACE

        trace = TRACE.get("overload:1")
        assert trace is not None
        assert trace["spans"][0]["name"] == "ingress.shed"
        # draining the backlog de-escalates one rung per cooldown,
        # never instantly
        server.broker.flush()
        assert ctl.evaluate(force=True) == MODE_EMERGENCY
        assert wait_until(
            lambda: ctl.evaluate(force=True) == MODE_SHEDDING,
            timeout=5.0,
        )
        assert wait_until(
            lambda: ctl.evaluate(force=True) == MODE_NORMAL,
            timeout=5.0,
        )
        # recovery closes the incident
        trace = TRACE.get("overload:1")
        assert trace["outcome"] == "recovered"
        assert "shed_total" in trace["attrs"]
    finally:
        server.stop()


def test_overload_disabled_by_knob(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD", "0")
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_DEPTH", "1")
    server = _stopped_server()
    try:
        _flood_broker(server, 50)
        assert server.overload.evaluate(force=True) == MODE_NORMAL
        ok, _retry = server.overload.admit(PRI_SUBMIT)
        assert ok
    finally:
        server.stop()


# -- HTTP 429 path -----------------------------------------------------


@pytest.fixture
def shedding_api(monkeypatch):
    """HTTP server held at SHEDDING: backlog between 1x and 4x the
    depth threshold."""
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_DEPTH", "8")
    server = _stopped_server()
    _flood_broker(server, 12)  # SHEDDING band: [8, 32)
    assert server.overload.evaluate(force=True) == MODE_SHEDDING
    http = start_http_server(server, port=0)
    yield server, f"http://127.0.0.1:{http.port}"
    http.stop()
    server.stop()


def test_http_submission_shed_with_retry_after(shedding_api):
    server, base = shedding_api
    job = {
        "ID": "shed-me",
        "Type": "service",
        "TaskGroups": [
            {
                "Name": "g",
                "Count": 1,
                "Tasks": [{"Name": "t", "Driver": "mock_driver"}],
            }
        ],
    }
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base, "/v1/jobs", {"Job": job})
    assert exc.value.code == 429
    retry_after = exc.value.headers.get("Retry-After")
    assert retry_after is not None and float(retry_after) >= 1
    body = json.loads(exc.value.read())
    assert body["Mode"] == "SHEDDING"
    # the job was never accepted
    assert server.store.job_by_id("default", "shed-me") is None
    assert server.metrics.get_counter("overload.shed") >= 1


def test_http_heartbeats_and_queries_survive_shedding(shedding_api):
    server, base = shedding_api
    node = mock.node()
    server.store.upsert_node(node)
    status, headers, _body = _post(
        base, f"/v1/node/{node.id}/heartbeat", {}
    )
    assert status == 200
    # queries (class 1) are above the default shed floor (2)
    jobs = _get(base, "/v1/jobs")
    assert isinstance(jobs, list)
    # observability endpoints always answer
    payload = _get(base, "/v1/overload")
    assert payload["mode_name"] == "SHEDDING"
    assert payload["signals"]["depth"] >= 8


def test_http_emergency_sheds_queries_never_heartbeats(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_DEPTH", "4")
    server = _stopped_server()
    _flood_broker(server, 40)  # >= 4x4: EMERGENCY
    assert server.overload.evaluate(force=True) == MODE_EMERGENCY
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base, "/v1/jobs")
        assert exc.value.code == 429
        assert float(exc.value.headers.get("Retry-After")) >= 1
        node = mock.node()
        server.store.upsert_node(node)
        status, _h, _b = _post(
            base, f"/v1/node/{node.id}/heartbeat", {}
        )
        assert status == 200
    finally:
        http.stop()
        server.stop()


def test_http_blocking_query_degrades_to_nonblocking(shedding_api):
    server, base = shedding_api
    index = server.store.latest_index()
    t0 = time.monotonic()
    # a blocking query past the latest index would normally park for
    # the full wait; under SHEDDING it answers immediately
    with urllib.request.urlopen(
        base + f"/v1/nodes?index={index + 100}&wait=5", timeout=10
    ) as resp:
        assert resp.status == 200
        assert resp.headers.get("X-Nomad-Index") is not None
    assert time.monotonic() - t0 < 2.0
    assert server.metrics.get_counter("overload.deferred") >= 1


def test_http_keepalive_survives_bodyless_handlers():
    """Regression: handlers that answered without reading the request
    body used to poison HTTP/1.1 keep-alive connections (the unread
    body parsed as the next request line -> 501)."""
    from nomad_tpu.loadgen.swarm import HttpSession

    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=7)
    server.start()
    http = start_http_server(server, port=0)
    try:
        node = mock.node()
        server.register_node(node)
        session = HttpSession("127.0.0.1", http.port)
        for _ in range(4):
            status, _h, _b = session.request(
                "POST", f"/v1/node/{node.id}/heartbeat", body={}
            )
            assert status == 200
        session.close()
    finally:
        http.stop()
        server.stop()


# -- mass node-death ---------------------------------------------------


def _running_world(server, n_nodes, n_jobs, count=1):
    """n_nodes registered + n_jobs placed and marked running."""
    nodes = [mock.node() for _ in range(n_nodes)]
    for n in nodes:
        server.register_node(n)
    jobs = []
    for i in range(n_jobs):
        job = mock.job(id=f"mass-{i:03d}")
        job.task_groups[0].count = count
        for tg in job.task_groups:
            for task in tg.tasks:
                task.resources.cpu = 50
                task.resources.memory_mb = 32
        server.register_job(job)
        jobs.append(job)
    assert server.drain_to_idle(20)
    running = []
    for job in jobs:
        for alloc in server.store.allocs_by_job("default", job.id):
            if not alloc.terminal_status():
                alloc.client_status = ALLOC_CLIENT_STATUS_RUNNING
                running.append(alloc)
    server.store.upsert_allocs(running)
    return nodes, jobs


def _all_replaced(server, jobs, dead_ids, count=1):
    for job in jobs:
        live = [
            a
            for a in server.store.allocs_by_job("default", job.id)
            if not a.terminal_status()
        ]
        if len(live) != count:
            return False
        if any(a.node_id in dead_ids for a in live):
            return False
    return True


def test_mass_death_one_batched_wave(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_WAVE_GATHER_S", "1.0")
    server = Server(num_schedulers=1, heartbeat_ttl=0.5, seed=3)
    server.start()
    try:
        nodes, jobs = _running_world(server, 12, 6)
        # every node stops heartbeating: one sweep catches the whole
        # cohort (deadlines were all set in the same register wave)
        assert wait_until(
            lambda: all(
                server.store.node_by_id(n.id).status
                == NODE_STATUS_DOWN
                for n in nodes
            ),
            timeout=10.0,
        )
        # ONE wave: one counter bump, one batched transition (every
        # downed node shares the wave's single index bump)
        assert (
            server.metrics.get_counter("overload.node_down_waves")
            == 1
        )
        assert (
            server.metrics.get_gauge("overload.last_wave_nodes")
            == 12.0
        )
        indices = {
            server.store.node_by_id(n.id).modify_index for n in nodes
        }
        assert len(indices) == 1
        # the replan evals share the wave's storm family hint
        hinted = {
            ev.family_hint
            for ev in server.store.evals.values()
            if ev.family_hint
        }
        assert hinted == {"node-down:w1"}
        # wave incident trace
        from nomad_tpu.trace import TRACE

        trace = TRACE.get("node_down_wave:1")
        assert trace is not None
        assert trace["attrs"]["nodes"] == 12
        assert trace["attrs"]["evals"] == 6
        # zero lost: nothing pending, failed queue empty (the world
        # has no live nodes left, so replans block/complete but the
        # evals must all be terminal or blocked-for-capacity)
        assert server.drain_to_idle(20)
        assert not server.broker.failed()
    finally:
        server.stop()


def test_heartbeat_mid_gather_prevents_false_down(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_WAVE_MIN", "2")
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_WAVE_GATHER_S", "3.0")
    server = Server(num_schedulers=1, heartbeat_ttl=0.6, seed=3)
    server.start()
    try:
        nodes = [mock.node() for _ in range(4)]
        for n in nodes:
            server.register_node(n)
        survivor = nodes[0]
        # keep ONE node heartbeating while the rest go dark; its TTL
        # expiry may enter the gather window between beats, but the
        # heartbeat must pull it back out before the wave commits
        stop = threading.Event()

        def beat():
            while not stop.is_set():
                try:
                    server.heartbeat(survivor.id)
                except KeyError:
                    pass
                stop.wait(0.15)

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            assert wait_until(
                lambda: all(
                    server.store.node_by_id(n.id).status
                    == NODE_STATUS_DOWN
                    for n in nodes[1:]
                ),
                timeout=10.0,
            )
            assert (
                server.store.node_by_id(survivor.id).status
                != NODE_STATUS_DOWN
            )
        finally:
            stop.set()
            t.join(timeout=5)
    finally:
        server.stop()


@pytest.mark.parametrize("storm_on", [True, False])
def test_mass_death_storm_recovery_and_serial_parity(
    monkeypatch, storm_on
):
    """A mass death replans every affected job with ZERO lost evals —
    through at most 2 global storm solves when the solver is on, and
    identically (every job fully replaced off the dead nodes) through
    the serial chain when it is off."""
    monkeypatch.setenv("NOMAD_TPU_STORM", "1" if storm_on else "0")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "4")
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_WAVE_GATHER_S", "1.0")
    server = Server(num_schedulers=1, heartbeat_ttl=0.6, seed=3)
    server.start()
    try:
        nodes, jobs = _running_world(server, 24, 8)
        victims = {
            a.node_id
            for job in jobs
            for a in server.store.allocs_by_job("default", job.id)
        }
        # keep every non-victim node alive
        survivors = [n for n in nodes if n.id not in victims]
        stop = threading.Event()

        def beat():
            while not stop.is_set():
                for n in survivors:
                    try:
                        server.heartbeat(n.id)
                    except KeyError:
                        pass
                stop.wait(0.15)

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            assert wait_until(
                lambda: all(
                    server.store.node_by_id(nid).status
                    == NODE_STATUS_DOWN
                    for nid in victims
                ),
                timeout=10.0,
            )
            # zero lost: every job fully replaced off the dead nodes
            assert wait_until(
                lambda: _all_replaced(server, jobs, victims),
                timeout=20.0,
            ), {
                job.id: [
                    (a.node_id in victims, a.client_status)
                    for a in server.store.allocs_by_job(
                        "default", job.id
                    )
                    if not a.terminal_status()
                ]
                for job in jobs
            }
            assert server.drain_to_idle(20)
            assert not server.broker.failed()
            solves = server.metrics.get_counter("storm.solves")
            if storm_on:
                # the wave rode the global solver, coalesced
                assert 1 <= solves <= 2, solves
            else:
                assert solves == 0
        finally:
            stop.set()
            t.join(timeout=5)
    finally:
        server.stop()


# -- sweeper hardening -------------------------------------------------


def test_sweeper_respawns_after_death():
    server = Server(num_schedulers=1, heartbeat_ttl=0.4, seed=3)
    server.start()
    try:
        node = mock.node()
        server.register_node(node)
        sweeper = server._heartbeat_sweeper
        assert sweeper is not None and sweeper.is_alive()
        # simulate a dead sweeper thread (crashed/never spawned)
        dead = threading.Thread(target=lambda: None)
        dead.start()
        dead.join()
        with server._sweeper_lock:
            server._heartbeat_sweeper = dead
        # the next heartbeat re-arms TTL enforcement
        server.heartbeat(node.id)
        assert server._heartbeat_sweeper is not dead
        assert server._heartbeat_sweeper.is_alive()
        # and TTL expiry still fires
        assert wait_until(
            lambda: server.store.node_by_id(node.id).status
            == NODE_STATUS_DOWN,
            timeout=10.0,
        )
    finally:
        server.stop()


def test_sweeper_survives_sweep_crash(monkeypatch):
    server = Server(num_schedulers=1, heartbeat_ttl=0.3, seed=3)
    server.start()
    try:
        node = mock.node()
        server.register_node(node)
        crashes = {"n": 0}
        original = server._sweep_once

        def flaky(interval):
            if crashes["n"] < 2:
                crashes["n"] += 1
                raise RuntimeError("injected sweep crash")
            return original(interval)

        monkeypatch.setattr(server, "_sweep_once", flaky)
        # the sweeper thread must survive the injected crashes and
        # still enforce the TTL afterwards
        assert wait_until(
            lambda: server.store.node_by_id(node.id).status
            == NODE_STATUS_DOWN,
            timeout=10.0,
        )
        assert crashes["n"] == 2
        assert server._heartbeat_sweeper.is_alive()
    finally:
        server.stop()
