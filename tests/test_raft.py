"""Raft core tests: election, replication, failover, partitions,
snapshot catch-up (the consensus behaviors the reference gets from
hashicorp/raft and exercises via in-process clusters,
nomad/testing.go:44 + leader_test.go)."""
import pickle
import threading
import time

import pytest

from nomad_tpu.raft import (
    InmemTransport,
    NotLeaderError,
    RaftNode,
)


class KVFSM:
    def __init__(self):
        self.data = {}
        self.lock = threading.Lock()
        self.applied = []

    def apply(self, raw):
        cmd = pickle.loads(raw)
        with self.lock:
            self.data[cmd["k"]] = cmd["v"]
            self.applied.append(cmd)
        return cmd["v"]

    def snapshot(self):
        with self.lock:
            return pickle.dumps(self.data)

    def restore(self, raw):
        with self.lock:
            self.data = pickle.loads(raw)


def make_cluster(n=3, snapshot_threshold=2048):
    transport = InmemTransport()
    addrs = [f"s{i}" for i in range(n)]
    nodes = []
    for addr in addrs:
        fsm = KVFSM()
        node = RaftNode(
            addr,
            addrs,
            transport,
            fsm,
            election_timeout=0.1,
            heartbeat_interval=0.02,
            snapshot_threshold=snapshot_threshold,
        )
        nodes.append(node)
    for node in nodes:
        node.start()
    return transport, nodes


def wait_for_leader(nodes, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [n for n in nodes if n.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no single leader elected")


def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def shutdown(nodes):
    for n in nodes:
        n.stop()


def put(node, k, v):
    return node.apply(pickle.dumps({"k": k, "v": v}))


def test_single_leader_elected():
    _, nodes = make_cluster(3)
    try:
        leader = wait_for_leader(nodes)
        # stable: still the only leader shortly after
        time.sleep(0.3)
        assert [n for n in nodes if n.is_leader()] == [leader]
        for n in nodes:
            assert n.leader_hint() == leader.addr
    finally:
        shutdown(nodes)


def test_apply_replicates_to_all():
    _, nodes = make_cluster(3)
    try:
        leader = wait_for_leader(nodes)
        assert put(leader, "a", 1) == 1
        assert put(leader, "b", 2) == 2
        wait_until(
            lambda: all(
                n.fsm.data == {"a": 1, "b": 2} for n in nodes
            ),
            msg="replication to all followers",
        )
    finally:
        shutdown(nodes)


def test_apply_on_follower_raises_with_hint():
    _, nodes = make_cluster(3)
    try:
        leader = wait_for_leader(nodes)
        follower = next(n for n in nodes if n is not leader)
        with pytest.raises(NotLeaderError) as exc:
            put(follower, "x", 1)
        assert exc.value.leader == leader.addr
    finally:
        shutdown(nodes)


def test_leader_failure_elects_new_and_preserves_log():
    transport, nodes = make_cluster(3)
    try:
        leader = wait_for_leader(nodes)
        put(leader, "a", 1)
        leader.stop()
        transport.set_down(leader.addr)
        rest = [n for n in nodes if n is not leader]
        new_leader = wait_for_leader(rest)
        assert new_leader is not leader
        put(new_leader, "b", 2)
        wait_until(
            lambda: all(
                n.fsm.data == {"a": 1, "b": 2} for n in rest
            ),
            msg="post-failover replication",
        )
    finally:
        shutdown([n for n in nodes if n._threads])


def test_partitioned_leader_steps_down_and_converges():
    transport, nodes = make_cluster(3)
    try:
        leader = wait_for_leader(nodes)
        put(leader, "a", 1)
        transport.isolate(leader.addr)
        rest = [n for n in nodes if n is not leader]
        new_leader = wait_for_leader(rest)
        put(new_leader, "b", 2)
        # writes on the stale leader cannot commit
        with pytest.raises((TimeoutError, NotLeaderError)):
            leader.apply(
                pickle.dumps({"k": "stale", "v": 9}), timeout=0.5
            )
        transport.heal()
        wait_until(
            lambda: not leader.is_leader(),
            msg="stale leader stepping down",
        )
        wait_until(
            lambda: all(
                n.fsm.data.get("b") == 2
                and "stale" not in n.fsm.data
                for n in nodes
            ),
            msg="convergence after heal",
        )
    finally:
        shutdown(nodes)


def test_snapshot_compaction_and_follower_catchup():
    transport, nodes = make_cluster(3, snapshot_threshold=20)
    try:
        leader = wait_for_leader(nodes)
        follower = next(n for n in nodes if n is not leader)
        transport.set_down(follower.addr)
        for i in range(60):
            put(leader, f"k{i}", i)
        wait_until(
            lambda: leader.log.snapshot_index > 0,
            msg="leader log compaction",
        )
        transport.set_down(follower.addr, down=False)
        wait_until(
            lambda: follower.fsm.data.get("k59") == 59,
            msg="follower catch-up via snapshot",
        )
        assert follower.log.snapshot_index > 0
    finally:
        shutdown(nodes)


def test_single_node_cluster_self_elects():
    transport = InmemTransport()
    fsm = KVFSM()
    node = RaftNode(
        "solo", ["solo"], transport, fsm,
        election_timeout=0.05, heartbeat_interval=0.02,
    )
    node.start()
    try:
        wait_until(node.is_leader, msg="self election")
        assert node.apply(pickle.dumps({"k": "a", "v": 1})) == 1
        assert fsm.data == {"a": 1}
    finally:
        node.stop()


def test_replicated_config_change_converges():
    """remove_server commits a KIND_CONFIG entry; every live node
    applies the same membership (the behavior the reference gets from
    raft.RemoveServer through the replicated log)."""
    _, nodes = make_cluster(5)
    try:
        leader = wait_for_leader(nodes)
        victim = next(n for n in nodes if n is not leader)
        victim.stop()
        leader.remove_server(victim.addr)
        live = [n for n in nodes if n is not victim]
        wait_until(
            lambda: all(
                victim.addr not in n.peers for n in live
            ),
            msg="all live nodes drop the removed peer",
        )
        # the shrunken cluster still commits
        assert put(leader, "after", 1) == 1
    finally:
        shutdown([n for n in nodes if n._threads])


def test_config_change_survives_snapshot_install():
    """A follower that catches up via install_snapshot receives the
    membership recorded at snapshot time."""
    transport, nodes = make_cluster(3, snapshot_threshold=8)
    # joins while the lagger is partitioned; election timeout is huge
    # so it stays a passive voter until the leader contacts it
    extra = RaftNode(
        "s-extra", [], transport, KVFSM(),
        election_timeout=1000.0, heartbeat_interval=0.02,
        snapshot_threshold=8,
    )
    extra.start()
    nodes.append(extra)
    try:
        leader = wait_for_leader(nodes[:3])
        lagger = next(n for n in nodes[:3] if n is not leader)
        for peer in nodes[:3]:
            if peer is not lagger:
                transport.partition(lagger.addr, peer.addr)
        leader.add_server(extra.addr)
        for i in range(20):  # force compaction past the config entry
            put(leader, f"k{i}", i)
        wait_until(
            lambda: leader.log.snapshot_index > 0,
            msg="leader compacts",
        )
        for peer in nodes:
            transport.heal(lagger.addr, peer.addr)
        wait_until(
            lambda: extra.addr in lagger.peers,
            msg="lagger learns the added server from the snapshot",
        )
    finally:
        shutdown(nodes)
