"""Churny multi-process soak (VERDICT r4 #3; reference e2e model:
e2e/e2e_test.go suites against a live cluster).

Boots a REAL cluster — 3 netagent server processes over framed TCP
raft + 3 netclient processes attached over HTTP — and churns it for
SOAK_SECONDS (default 180): job registrations with rolling-update
deployments, scale up/down, drains, client SIGKILLs with node purges,
high-priority preemption bursts, and job stops, with streaming
consumers attached the whole time (chunked /v1/agent/monitor and a
`logs -f`-style follower).  At the end the cluster must CONVERGE:
every live job fully placed with a successful deployment, no
non-terminal evals, no allocs leaked on dead nodes, all three servers
agreeing, and the streams still live (not stuck, not dead).

Run with:  pytest -m slow tests/test_soak.py  (env SOAK_SECONDS=...)
"""
from __future__ import annotations

import http.client
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        os.environ.get("NOMAD_TPU_SOAK") != "1",
        reason="opt-in soak: set NOMAD_TPU_SOAK=1 "
        "(and optionally SOAK_SECONDS) to run",
    ),
]

SOAK_SECONDS = float(os.environ.get("SOAK_SECONDS", 180))


def free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, timeout=10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return json.loads(resp.read() or b"null")


def _post(port, path, payload, timeout=15.0, method="POST"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"null")


def _service_job(jid, count, priority=50, spread=False,
                 cpu=100, canary=False):
    tg = {
        "name": "w",
        "count": count,
        "update": {
            "max_parallel": 2,
            "min_healthy_time": 0,
            "healthy_deadline": 30,
        },
        "tasks": [
            {
                "name": "t",
                "driver": "mock_driver",
                "config": {"run_for": -1},
                "resources": {"cpu": cpu, "memory_mb": 32},
            }
        ],
    }
    job = {
        "id": jid,
        "type": "service",
        "priority": priority,
        "datacenters": ["dc1"],
        "task_groups": [tg],
    }
    if spread:
        job["spreads"] = [
            {"attribute": "${node.datacenter}", "weight": 50}
        ]
    return job


class _MonitorStream(threading.Thread):
    """Chunked /v1/agent/monitor consumer: proves the streaming
    transport survives the churn (bytes keep flowing, clean shutdown,
    never wedges the server)."""

    def __init__(self, port):
        super().__init__(daemon=True)
        self.port = port
        self.received = 0
        self.error = None
        self._stop = threading.Event()

    def run(self):
        import select as _select
        import socket as _socket

        try:
            sock = _socket.create_connection(
                ("127.0.0.1", self.port), timeout=10
            )
            sock.sendall(
                b"GET /v1/agent/monitor?follow=true&plain=true "
                b"HTTP/1.1\r\nHost: localhost\r\n\r\n"
            )
            sock.setblocking(False)
            while not self._stop.is_set():
                r, _w, _x = _select.select([sock], [], [], 1.0)
                if not r:
                    continue  # idle stream: quiet periods are normal
                data = sock.recv(4096)
                if not data:
                    break
                self.received += len(data)
            sock.close()
        except Exception as exc:  # noqa: BLE001
            if not self._stop.is_set():
                self.error = exc

    def stop(self):
        self._stop.set()


class _LogFollower(threading.Thread):
    """Follows a running alloc's stdout via the follow=true chunked
    endpoint, re-attaching to a fresh alloc when its current one
    dies — the `alloc logs -f` consumer in the soak."""

    def __init__(self, port):
        super().__init__(daemon=True)
        self.port = port
        self.attaches = 0
        self.error = None
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            alloc_id = self._pick_alloc()
            if alloc_id is None:
                time.sleep(1.0)
                continue
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", self.port, timeout=20
                )
                conn.request(
                    "GET",
                    f"/v1/client/fs/logs/{alloc_id}"
                    "?task=t&type=stdout&follow=true",
                )
                resp = conn.getresponse()
                if resp.status != 200:
                    resp.read()
                    conn.close()
                    time.sleep(0.5)
                    continue
                self.attaches += 1
                deadline = time.monotonic() + 10.0
                while (
                    not self._stop.is_set()
                    and time.monotonic() < deadline
                ):
                    resp.fp.raw._sock.settimeout(2.0)
                    try:
                        if not resp.read1(4096):
                            break
                    except Exception:  # noqa: BLE001
                        continue
                conn.close()
            except Exception:  # noqa: BLE001
                time.sleep(0.5)

    def _pick_alloc(self):
        try:
            allocs = _get(self.port, "/v1/allocations")
        except Exception:  # noqa: BLE001
            return None
        for a in allocs:
            if a.get("client_status") == "running":
                return a["id"]
        return None

    def stop(self):
        self._stop.set()


def _soak_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the conftest exports SYNC_COMPILE=1 for deterministic prescore
    # assertions; in the soak it would stall workers on foreground
    # XLA compiles — the production behavior (background compile +
    # sequential fallback) is exactly what we're soaking
    env.pop("NOMAD_TPU_SYNC_COMPILE", None)
    return env


def _spawn_server(addr, peers, http_port, join=None):
    env = _soak_env()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, "-m", "nomad_tpu.server.netagent",
        "--addr", addr, "--peers", peers,
        "--http-port", str(http_port),
        "--heartbeat-ttl", "10",
    ]
    if join:
        cmd += ["--join", join]
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=env, cwd=repo,
    )


def _spawn_client(server_ports, data_dir):
    env = _soak_env()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, "-m", "nomad_tpu.client.netclient",
        "--servers",
        ",".join(f"http://127.0.0.1:{p}" for p in server_ports),
        "--data-dir", data_dir,
        "--heartbeat-interval", "2",
    ]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=env, cwd=repo,
    )
    line = proc.stdout.readline().decode()
    assert line.startswith("READY"), line
    node_id = line.split()[1]
    return proc, node_id


def _wait(cond, what, timeout=60, interval=0.5):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = cond()
            if last:
                return last
        except Exception as exc:  # noqa: BLE001
            last = exc
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}: {last!r}")


@pytest.mark.slow
def test_cluster_soak(tmp_path):
    rng = random.Random(4242)
    rpc_ports = [free_port() for _ in range(3)]
    http_ports = [free_port() for _ in range(3)]
    addrs = [f"127.0.0.1:{p}" for p in rpc_ports]
    peers = ",".join(addrs)

    servers = []
    clients = {}  # node_id -> proc
    streams = []
    killed_nodes = []
    live_jobs = {}  # jid -> expected count
    stopped_jobs = set()
    seq = 0

    def any_port():
        return rng.choice(http_ports)

    try:
        for i in range(3):
            servers.append(
                _spawn_server(
                    addrs[i], peers, http_ports[i],
                    join=addrs[0] if i else None,
                )
            )
        for p in servers:
            line = p.stdout.readline().decode()
            assert line.startswith("READY"), line
        _wait(
            lambda: any(
                _get(p, "/v1/status/leader") for p in http_ports
            ),
            "leader election",
        )
        # preemption on for the high-priority bursts (retried: the
        # fresh leader may still be establishing)
        def _enable_preemption():
            cfg = _get(
                http_ports[0],
                "/v1/operator/scheduler/configuration",
            )
            cfg["PreemptionConfig"] = {
                "ServiceSchedulerEnabled": True,
                "BatchSchedulerEnabled": True,
            }
            _post(
                http_ports[0],
                "/v1/operator/scheduler/configuration", cfg,
            )
            return True

        _wait(_enable_preemption, "preemption config applied")

        for i in range(3):
            proc, node_id = _spawn_client(
                http_ports, str(tmp_path / f"client{i}")
            )
            clients[node_id] = proc
        _wait(
            lambda: sum(
                n["Status"] == "ready"
                for n in _get(http_ports[0], "/v1/nodes")
            )
            == 3,
            "3 ready nodes",
        )

        # streaming consumers ride along for the whole soak
        mon = _MonitorStream(http_ports[0])
        mon.start()
        streams.append(mon)
        follower = _LogFollower(http_ports[1])
        follower.start()
        streams.append(follower)

        def submit(job):
            _post(any_port(), "/v1/jobs", {"Job": job})

        # seed load
        for _ in range(3):
            seq += 1
            jid = f"svc-{seq}"
            live_jobs[jid] = 2
            submit(_service_job(jid, 2, spread=bool(seq % 2)))

        deadline = time.monotonic() + SOAK_SECONDS
        it = 0
        while time.monotonic() < deadline:
            it += 1
            action = rng.random()
            try:
                if action < 0.30:
                    # register a new service job (deployment churn)
                    seq += 1
                    jid = f"svc-{seq}"
                    count = rng.randint(1, 4)
                    live_jobs[jid] = count
                    submit(
                        _service_job(
                            jid, count, spread=bool(seq % 3 == 0)
                        )
                    )
                elif action < 0.45 and live_jobs:
                    # scale an existing job
                    jid = rng.choice(list(live_jobs))
                    count = rng.randint(1, 5)
                    live_jobs[jid] = count
                    _post(
                        any_port(), f"/v1/job/{jid}/scale",
                        {
                            "Target": {"Group": "w"},
                            "Count": count,
                        },
                    )
                elif action < 0.55 and len(live_jobs) > 2:
                    # stop + purge a job
                    jid = rng.choice(list(live_jobs))
                    del live_jobs[jid]
                    stopped_jobs.add(jid)
                    _post(
                        any_port(),
                        f"/v1/job/{jid}?purge=true",
                        {},
                        method="DELETE",
                    )
                elif action < 0.65:
                    # high-priority preemption burst (short-lived)
                    seq += 1
                    jid = f"vip-{seq}"
                    live_jobs[jid] = 1
                    submit(
                        _service_job(
                            jid, 1, priority=90, cpu=400
                        )
                    )
                elif action < 0.80 and len(clients) > 1:
                    # drain a node, then lift the drain
                    node_id = rng.choice(list(clients))
                    _post(
                        any_port(), f"/v1/node/{node_id}/drain",
                        {"DrainSpec": {"Deadline": 30e9}},
                    )
                    time.sleep(2.0)
                    _post(
                        any_port(), f"/v1/node/{node_id}/drain",
                        {},
                    )
                    _post(
                        any_port(),
                        f"/v1/node/{node_id}/eligibility",
                        {"Eligibility": "eligible"},
                    )
                elif len(clients) > 2:
                    # SIGKILL a client; replace it with a fresh one
                    node_id = rng.choice(list(clients))
                    proc = clients.pop(node_id)
                    proc.kill()
                    proc.wait(timeout=5)
                    killed_nodes.append(node_id)
                    new_proc, new_id = _spawn_client(
                        http_ports,
                        str(tmp_path / f"client-r{it}"),
                    )
                    clients[new_id] = new_proc
            except (
                urllib.error.HTTPError,
                urllib.error.URLError,
                ConnectionError,
                OSError,
            ):
                # transient churn races (404 on a just-purged job,
                # leader transition, a killed client's socket) are
                # part of the exercise
                pass
            time.sleep(rng.uniform(0.5, 1.5))

        # ---- quiesce: stop the churn and demand convergence --------
        # trim to what a 3-node fleet can definitely place
        for jid in sorted(live_jobs)[6:]:
            stopped_jobs.add(jid)
            del live_jobs[jid]
            try:
                _post(
                    http_ports[0],
                    f"/v1/job/{jid}?purge=true", {},
                    method="DELETE",
                )
            except (urllib.error.HTTPError, urllib.error.URLError,
                    OSError):
                pass
        # dead nodes: purge so their allocs can't linger
        for node_id in killed_nodes:
            try:
                _post(
                    http_ports[0], f"/v1/node/{node_id}/purge", {}
                )
            except (urllib.error.HTTPError, urllib.error.URLError,
                    OSError):
                pass

        state = {}

        def converged():
            ok = True
            for jid, want in live_jobs.items():
                allocs = _get(
                    http_ports[0], f"/v1/job/{jid}/allocations"
                )
                running = sum(
                    a["client_status"] == "running"
                    and a["desired_status"] == "run"
                    for a in allocs
                )
                state[jid] = (
                    want, running,
                    sorted(
                        (a["client_status"], a["desired_status"])
                        for a in allocs
                    ),
                )
                if running != want:
                    ok = False
            return ok

        try:
            _wait(
                converged, "all live jobs fully placed", timeout=120
            )
        except AssertionError:
            nodes_dbg = [
                (n["ID"][:8], n["Status"],
                 n["SchedulingEligibility"])
                for n in _get(http_ports[0], "/v1/nodes")
            ]
            evs_dbg = [
                (e["job_id"], e["status"],
                 e.get("status_description", ""))
                for e in _get(http_ports[0], "/v1/evaluations")
                if e["status"]
                not in ("complete", "canceled")
            ]
            raise AssertionError(
                f"not converged: {state}\nnodes={nodes_dbg}\n"
                f"evals={evs_dbg}"
            )

        # no non-terminal evals anywhere
        def evals_quiet():
            evs = _get(http_ports[0], "/v1/evaluations")
            bad = [
                e
                for e in evs
                if e["status"] not in ("complete", "canceled", "failed")
                and e["job_id"] in live_jobs
            ]
            return not bad

        _wait(evals_quiet, "no stuck evals for live jobs", timeout=60)

        # no allocs still claiming dead (killed) nodes
        def dead_nodes_clear():
            allocs = _get(http_ports[0], "/v1/allocations")
            for a in allocs:
                if a["node_id"] in killed_nodes:
                    if a["client_status"] not in (
                        "lost", "complete", "failed",
                    ):
                        return False
            return True

        _wait(
            dead_nodes_clear, "no live allocs on killed nodes",
            timeout=60,
        )

        # every server replica agrees on the job set and live counts
        def servers_agree():
            views = []
            for p in http_ports:
                jobs = {
                    j["ID"]: j["Status"]
                    for j in _get(p, "/v1/jobs")
                }
                views.append(jobs)
            return views[0] == views[1] == views[2]

        _wait(servers_agree, "server replicas agree", timeout=60)

        # streams: alive the whole run, bytes flowed, no errors
        assert mon.error is None, mon.error
        assert mon.received > 0
        assert follower.attaches > 0
        assert follower.error is None, follower.error
    finally:
        for s in streams:
            s.stop()
        for proc in clients.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in clients.values():
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        for p in servers:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in servers:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
